#! /bin/bash
# Batch-experiment harness — capability parity with the reference's
# experiments.sh (reference: experiments.sh:19-55): loops
# `run <experiment> <gar> <n> <f> <batch> <steps>` invocations of the CLI
# runner, capturing stdout/stderr per configuration under names
# E=..-R=..-N=..-F=..-B=.. so traces from reference-driven scripts carry over.
#
# There is no cluster to start or stop: the single-controller SPMD runtime
# replaces the reference's deploy.py parameter-server bring-up (its
# start_cluster/stop_cluster, experiments.sh:7-17). Multi-host TPU pods are
# launched by running this same script on every host (JAX's multi-process
# runtime; see aggregathor_tpu/cli/deploy.py).

set -u

RESULTS_DIR="${RESULTS_DIR:-results}"
PLATFORM_ARGS=${PLATFORM_ARGS:-}    # extra runner flags, e.g.
                                    #   "--platform cpu --nb-devices 8"
                                    # or the TPU-lean input path (r4):
                                    #   "--unroll 10 --input-source device"
RUNNING_PID=0

mkdir -p "${RESULTS_DIR}"

function run {
	local NAME=E=${1}-R=${2}-N=${3}-F=${4}-B=${5}
	python3 -m aggregathor_tpu.cli.runner \
		--experiment "${1}" \
		--aggregator "${2}" \
		--nb-workers "${3}" \
		--nb-decl-byz-workers "${4}" \
		--experiment-args "batch-size:${5}" \
		--max-step "${6}" \
		--stdout-to "${RESULTS_DIR}/${NAME}.stdout" \
		--stderr-to "${RESULTS_DIR}/${NAME}.stderr" \
		--evaluation-file "${RESULTS_DIR}/${NAME}.eval" \
		--evaluation-period -1 \
		--checkpoint-period 600 \
		--checkpoint-dir "${RESULTS_DIR}/${NAME}.ckpt" \
		--summary-period -1 \
		--evaluation-delta 1000 \
		--checkpoint-delta -1 \
		--summary-delta 1000 \
		${PLATFORM_ARGS} &
	RUNNING_PID=$!
	wait ${RUNNING_PID}
}

function run_abort {
	kill -s 2 ${RUNNING_PID} 2>/dev/null
	wait ${RUNNING_PID} 2>/dev/null
	exit 0
}

trap run_abort TERM INT

# Like `run`, but through the fully-sharded engine:
# run_sharded <experiment> <gar> <W> <PP> <TP> <f> <batch> <steps>
# (per-layer robust aggregation on a worker x pipeline x tensor mesh)
function run_sharded {
	local NAME=E=${1}-R=${2}-MESH=${3}x${4}x${5}-F=${6}-B=${7}
	python3 -m aggregathor_tpu.cli.runner \
		--experiment "${1}" \
		--aggregator "${2}" \
		--nb-workers "${3}" \
		--mesh "${3},${4},${5}" \
		--granularity layer \
		--nb-decl-byz-workers "${6}" \
		--experiment-args "batch-size:${7}" \
		--max-step "${8}" \
		--stdout-to "${RESULTS_DIR}/${NAME}.stdout" \
		--stderr-to "${RESULTS_DIR}/${NAME}.stderr" \
		--evaluation-file "${RESULTS_DIR}/${NAME}.eval" \
		--evaluation-period -1 --evaluation-delta 1000 \
		--checkpoint-period 600 --checkpoint-delta -1 \
		--checkpoint-dir "${RESULTS_DIR}/${NAME}.ckpt" \
		--summary-period -1 --summary-delta 1000 \
		${PLATFORM_ARGS} &
	RUNNING_PID=$!
	wait ${RUNNING_PID}
}

# Begin experiments (reference default: run mnist average 2 0 50 100000)
run mnist average 2 0 50 10000
# Extras this framework adds over the reference (uncomment to run):
#   REAL data with zero egress — sklearn digits to ~96% under Multi-Krum
#   (docs/robustness.md "Measured on REAL data"):
# run digits krum 8 2 32 4000
#   the cnnet conv topology on the same REAL corpus at 32x32 (~0.975 under
#   Multi-Krum; the conv-scale anchor — docs/robustness.md "Why not real
#   CIFAR-10"):
# run digits-conv krum 8 2 16 400
#   per-layer Krum on the dp x pp x tp transformer (BASELINE config 5):
# run_sharded transformer krum 4 2 1 1 16 1000
#   accuracy-under-attack sweep (docs/robustness.md):
# python3 benchmarks/robustness.py --experiment digits --steps 500 --batch 32
# End experiments

#!/usr/bin/env bash
# Flight-recorder smoke on CPU (<45 s), docs/observability.md "Device-side
# observability": a real CLI training run with the in-scan flight recorder
# and the live exporter on — then assert
#   1. a mid-run scrape of the LIVE training process answers /metrics
#      (strict Prometheus round-trip) and /status (flight window rows),
#   2. nonzero flight_fetches_total and a compile-event counter
#      (compile_cache_misses_total names the step executable),
#   3. the regression sentinel loads the baseline seeded by a first capture
#      run and emits a verdict (slo_verdict summary event + document),
#   4. the final --metrics-file flush parses after the process exits.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_flight}"
run_id="flsmoke01"
rm -rf "$out"
mkdir -p "$out/sum"

base=(--experiment mnist --experiment-args batch-size:16
      --aggregator median --nb-workers 4 --nb-decl-byz-workers 1
      --learning-rate-args initial-rate:0.05 --prefetch 0
      --evaluation-delta -1 --evaluation-period -1)

# ---- seed the SLO baseline from a fresh capture run ------------------- #
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner "${base[@]}" \
  --max-step 24 --unroll 4 --summary-delta 8 \
  --flight 16 --slo-capture "$out/slo.json"
test -s "$out/slo.json" || { echo "no SLO baseline captured"; exit 1; }

# ---- the main run: recorder + live exporter + sentinel, scraped LIVE -- #
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner "${base[@]}" \
  --max-step 400 --unroll 4 --summary-delta 8 \
  --flight 16 --run-id "$run_id" \
  --live-port 0 --live-ready-file "$out/ready" \
  --slo-baseline "$out/slo.json" --slo-verdict "$out/verdict.json" \
  --summary-dir "$out/sum" --metrics-file "$out/train.prom" \
  >"$out/train.log" 2>&1 &
train_pid=$!

python - "$out" "$run_id" <<'EOF'
import json, os, sys, time, urllib.request

from aggregathor_tpu.obs.metrics import parse_prometheus

out, run_id = sys.argv[1], sys.argv[2]

addr = None
for _ in range(600):  # the ready-file handshake (exporter binds pre-compile)
    try:
        addr = open(os.path.join(out, "ready")).read().split()
        break
    except OSError:
        time.sleep(0.1)
assert addr, "live exporter never published its address"
base = "http://%s:%s" % (addr[0], addr[1])

# ---- mid-run scrape: /metrics + /status from the TRAINING process ----- #
parsed = status = None
for _ in range(2000):
    try:
        text = urllib.request.urlopen(base + "/metrics", timeout=5).read().decode()
        candidate = parse_prometheus(text)            # strict round-trip
        fetches = dict((n, v) for n, l, v in
                       candidate.get("flight_fetches_total", {}).get("samples", []))
        if fetches.get("flight_fetches_total", 0.0) >= 1.0:
            parsed = candidate
            status = json.loads(urllib.request.urlopen(
                base + "/status", timeout=5).read())
            break
    except OSError:
        pass
    time.sleep(0.02)
assert parsed is not None, "never scraped a nonzero flight fetch mid-run"
assert status["run_id"] == run_id and status["step"] > 0, status
assert status["flight"]["rows"] >= 1, status["flight"]

# nonzero ring fetches + the compile-event counter naming the executable
fetches = dict((n, v) for n, l, v in parsed["flight_fetches_total"]["samples"])
assert fetches["flight_fetches_total"] >= 1.0, fetches
compiles = parsed["compile_cache_misses_total"]["samples"]
by_exec = dict((l["executable"], v) for n, l, v in compiles)
assert by_exec.get("train_multi_step", 0.0) >= 1.0, by_exec
backend = dict((n, v) for n, l, v in parsed["compile_backend_total"]["samples"])
assert backend["compile_backend_total"] >= 1.0, backend
print("live scrape OK: step %d, %d flight row(s), compile events %r"
      % (status["step"], status["flight"]["rows"], by_exec))
EOF

wait "$train_pid" || { echo "training run failed"; tail "$out/train.log"; exit 1; }

python - "$out" "$run_id" <<'EOF'
import json, os, sys

from aggregathor_tpu.obs.metrics import parse_prometheus
from aggregathor_tpu.obs import slo

out, run_id = sys.argv[1], sys.argv[2]

# ---- sentinel verdict: document + summary event ----------------------- #
verdict = json.load(open(os.path.join(out, "verdict.json")))
assert verdict["schema"] == slo.SCHEMA + ".verdict", verdict["schema"]
assert verdict["verdict"] in ("PASS", "REGRESS"), verdict
checked = [c for c in verdict["checks"] if c["status"] != "skipped"]
assert checked, "sentinel checked nothing"
events = [json.loads(line)
          for name in os.listdir(os.path.join(out, "sum"))
          for line in open(os.path.join(out, "sum", name))]
slo_events = [e for e in events if e.get("event") == "slo_verdict"]
assert slo_events and slo_events[0]["verdict"] == verdict["verdict"]
assert all(e.get("run_id") == run_id for e in events)
print("sentinel OK: %s on %s" % (
    verdict["verdict"], [c["metric"] for c in checked]))

# ---- final --metrics-file flush after process exit -------------------- #
parsed = parse_prometheus(open(os.path.join(out, "train.prom")).read())
steps = dict((n, v) for n, l, v in parsed["train_steps_total"]["samples"])
assert steps["train_steps_total"] >= 400.0, steps
last = dict((n, v) for n, l, v in parsed["flight_last_step"]["samples"])
assert last["flight_last_step"] == 400.0, last
print("final exposition OK: %d families, flight_last_step %d"
      % (len(parsed), last["flight_last_step"]))
EOF

echo "flight smoke OK: $out"

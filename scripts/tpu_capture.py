"""TPU up-window watcher: capture every pending BENCHMARKS.md cell.

The one TPU chip in this environment wedges for multi-hour windows
(BENCHMARKS.md "TPU caveat": backend init or the first host fetch hangs
indefinitely and uninterruptibly).  Sitting in front of the chip hoping a
benchmark run overlaps an up-window wasted two rounds; this watcher inverts
the strategy:

  loop:
    probe the chip (512x512 matmul + HOST FETCH under a hard timeout —
    only a host fetch actually syncs the tunneled backend);
    if alive: run the capture stages SERIALLY (the chip is single-tenant),
      each under its own watchdog, appending every JSON result line to
      ``benchmarks/tpu_capture.jsonl``;
    else: sleep and re-probe.

Stages (the "*pending*" cells of BENCHMARKS.md §1-2):

  bench_mini      — config-2 at full batch but a short scan (K=10 via
                    GRAFT_BENCH_SIZING): first, so a ~10 min up-window
                    still banks a real TPU training datum with MFU
  bench           — headline config-2 steps/s (bench.py, own watchdog)
  pallas_check    — Pallas kernels compiled on silicon, parity + ms
                    (scripts/pallas_tpu_check.py)
  gar_kernels     — per-rule kernel ms vs d, jnp:tpu + pallas tiers
  train_configs   — configs 2, 2b, 2d (device-sampled), 2c through the
                    real CLI on TPU
  opt_sweep       — unroll x dtype x augment x input ladder on config 2
                    (the VERDICT-r3 task-3 optimizer; per-combo resumable)
  train_configs34 — configs 3 (ResNet-50+Bulyan n=32 f=7 — BASELINE's f=8
                    violates Bulyan's n >= 4f+3 bound), 3k (ResNet-50+Krum
                    at the prescribed n=32 f=8), 3d (3k device-sampled) and
                    4 (Inception-v3+median under attack, n=32 f=8),
                    through the real CLI on TPU
  leaf_resnet     — per-layer granularity on a slim ResNet (the bucketed
                    leaf path) through the real CLI
  trace           — config 2b sizing with a jax.profiler trace banked to
                    benchmarks/trace_r03 for offline MFU attribution
  robustness      — accuracy-under-attack table at conv scale (cnnet)
  sharded_transformer — BASELINE config 5's machinery on a 1,1,1 mesh
                    (pipeline/ring/MoE code path on silicon)
  leaf_transformer — config 5f: per-layer Krum on a transformer with 8
                    vmapped workers via the flat engine's leaf path
  mfu_probe       — the >10% MFU demonstration: compute-dense robust
                    training (ResNet-50 @224, n=8 krum, batch 16/worker,
                    bf16, device-sampled input) — the BASELINE configs
                    are bandwidth-bound by their own envelopes

A stage that succeeds is recorded in ``scripts/tpu_capture_state.json`` and
not re-run, so a short up-window makes incremental progress and the next
window resumes where the last one wedged.  A stage timeout means the chip
wedged mid-pass: the child process group is killed (bounded grace — a
D-state child is abandoned, see bench.py), the watcher goes back to probing.

Usage::

    python scripts/tpu_capture.py [--once] [--stages bench,gar_kernels]
                                  [--sleep 600] [--fresh]
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STATE_PATH = os.path.join(REPO, "scripts", "tpu_capture_state.json")
LOG_PATH = os.path.join(REPO, "benchmarks", "tpu_capture.jsonl")

sys.path.insert(0, REPO)
from aggregathor_tpu.utils.capture import is_complete_tpu_datum as _tpu_datum  # noqa: E402
from aggregathor_tpu.utils.state import load_json, save_json_atomic  # noqa: E402

PROBE_CODE = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((512, 512), jnp.float32);"
    "print('PROBE_OK', float((x @ x)[0, 0]), jax.devices()[0].platform)"
)


def _stages(py):
    b = lambda *a: [py] + list(a)
    # Ordered by evidence-per-second: bench_mini first — the SAME config-2
    # program at batch 128, just a shorter scan (K=10) and fewer timed
    # loops, so even a ~10 min up-window banks a real TPU training datum
    # with MFU before anything heavier is attempted.  Then pallas_check
    # (small compiles, and the on-silicon Pallas proof is the single
    # highest-value pending cell), then the full headline bench; the
    # multi-config CLI drives last.  A stage entry may carry a 4th element:
    # extra environment for the child.
    return [
        # (name, argv, timeout_s[, extra_env])
        ("bench_mini", b("bench.py"), 1600, {"GRAFT_BENCH_SIZING": "128,10,3"}),
        ("pallas_check",
         b("scripts/pallas_tpu_check.py", "--n", "32", "--f", "8",
           "--dims", "65536,1048576,8388608"), 2400),
        # 2200 s: bench.py's own child watchdogs total 90 (probe) + 1500
        # (TPU attempt) + 480 (CPU fallback); every completed phase flushes
        # an updated result line, so a long leash risks no evidence.
        ("bench", b("bench.py"), 2200),
        ("gar_kernels",
         b("benchmarks/gar_kernels.py", "--n", "32", "--f", "8",
           "--dims", "65536,1048576,8388608", "--reps", "10",
           "--resume-file", "benchmarks/resume_gar_kernels.json"), 3600),
        ("train_configs",
         b("benchmarks/train_configs.py", "--configs", "2,2b,2d,2c",
           "--steps", "40", "--platform", "tpu", "--timeout", "1200",
           "--resume-file", "benchmarks/resume_train_configs.json"), 5400),
        # The VERDICT-r3 task-3 optimizer: sweep unroll x dtype x augment x
        # input sourcing on the real config-2 program; per-combo resumable,
        # one row per combination plus opt_sweep_best (trainable) and
        # opt_sweep_best_compute (resident upper bound) summary rows.
        # AFTER the unique evidence cells (pallas/bench/gar/train_configs):
        # optimization must not cost pending evidence its up-window.
        ("opt_sweep",
         b("benchmarks/opt_sweep.py", "--platform", "tpu", "--steps", "60",
           "--resume-file", "benchmarks/resume_opt_sweep.json"), 4800),
        ("train_configs34",
         b("benchmarks/train_configs.py", "--configs", "3,3k,3d,4",
           "--steps", "10", "--platform", "tpu", "--timeout", "1800",
           "--resume-file", "benchmarks/resume_train_configs34.json"), 7800),
        ("leaf_resnet",
         b("benchmarks/train_configs.py", "--configs", "6,6u",
           "--steps", "10", "--platform", "tpu", "--timeout", "1800",
           "--resume-file", "benchmarks/resume_leaf_resnet.json"), 4200),
        ("trace",
         b("benchmarks/train_configs.py", "--configs", "2t",
           "--steps", "40", "--platform", "tpu", "--timeout", "1500"), 1800),
        # BASELINE config 5 (stretch), both single-chip expressions: the
        # sharded engine's full machinery on a 1,1,1 mesh (pipeline/ring/MoE
        # code path on silicon) and per-layer Krum with real worker
        # multiplicity via the flat engine's bucketed leaf path.
        ("sharded_transformer",
         b("benchmarks/sharded_transformer.py", "--mesh", "1,1,1",
           "--gar", "median", "--d-model", "512", "--layers", "8",
           "--seq", "512", "--batch", "8", "--steps", "10",
           "--platform", "tpu"), 2400),
        ("leaf_transformer",
         b("benchmarks/train_configs.py", "--configs", "5f",
           "--steps", "20", "--platform", "tpu", "--timeout", "1500"), 1800),
        # The >10% MFU demonstration: BASELINE configs are bandwidth-bound
        # (config 2 by the model's own intensity, config 3 by the GAR's
        # batch-independent n*d gradient traffic — BENCHMARKS.md); this is
        # the compute-dense robust-training shape that can actually show
        # MXU utilization (ResNet-50 @224, n=8 krum, batch 16/worker,
        # bf16, device-sampled input).
        ("mfu_probe",
         b("benchmarks/mfu_probe.py", "--platform", "tpu",
           "--steps", "30", "--unroll", "10"), 2400),
        # Device-sampled input (same training distribution, different PRNG
        # stream) + unroll: a 300-step cell pays the tunnel once for the
        # dataset instead of 300 times for batches — the 13x input-path
        # difference is what makes a 12-cell accuracy grid fit an up-window.
        ("robustness",
         b("benchmarks/robustness.py", "--experiment", "cnnet", "--steps", "300",
           "--batch", "32", "--rules", "average,krum,median,dnc",
           "--platform", "tpu", "--timeout", "600",
           "--experiment-args-extra", "augment:device",
           "--runner-args", "--unroll 10 --input-source device",
           "--resume-file", "benchmarks/resume_robustness.json"), 8400),
        # VERDICT r4 task 4: kernel ms at reference-plausible worker counts
        # (compile time is the claim; it is stated per-cell as compile_s).
        ("scale_n",
         b("benchmarks/gar_kernels.py", "--rules", "", "--dims", "",
           "--scale-ns", "128,512,1024", "--scale-d", "65536", "--reps", "10",
           "--resume-file", "benchmarks/resume_scale_n.json"), 2400),
        # VERDICT r4 task 3 (conv-scale REAL-data robustness): the cnnet
        # topology on real digits32 (docs/robustness.md "Why not real
        # CIFAR-10"), device-sampled so 600-step cells fit the window.
        ("digits_conv_robustness",
         b("benchmarks/robustness.py", "--experiment", "digits-conv",
           "--steps", "600", "--batch", "32", "--rules", "average,krum,median",
           "--attacks", "none,little,empire",
           "--platform", "tpu", "--timeout", "600",
           "--runner-args", "--unroll 10 --input-source device",
           "--resume-file", "benchmarks/resume_digits_conv.json"), 6000),
        # VERDICT r4 task 6: zoo accuracy-parity spot check — ResNet-50
        # (GroupNorm variant) on REAL data (digits32) through the real CLI,
        # clean + Krum, device-sampled input.
        ("zoo_parity",
         b("benchmarks/robustness.py", "--experiment", "slim-resnet_v1_50-digits32",
           "--steps", "2000", "--batch", "32", "--rules", "average,krum",
           "--attacks", "none", "--platform", "tpu", "--timeout", "1500",
           "--experiment-args-extra", "preprocessing:none augment:device",
           "--runner-args", "--unroll 10 --input-source device",
           "--resume-file", "benchmarks/resume_zoo_parity.json"), 3600),
    ]


def _load_state():
    return load_json(STATE_PATH, default={"done": []})


def _save_state(state):
    save_json_atomic(STATE_PATH, state)


def _log(record):
    record["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(LOG_PATH, "a") as fd:
        fd.write(json.dumps(record) + "\n")
    print("capture: %s" % json.dumps(record)[:400], flush=True)


def _run_guarded(argv, timeout, env=None):
    """Run one child in its own session; killpg + bounded grace on timeout.

    Same rationale as bench.py's watchdog: ``subprocess.run(timeout=...)``
    waits UNBOUNDED after kill(), which never returns for a child stuck in
    an uninterruptible sleep inside the wedged accelerator driver.
    """
    proc = subprocess.Popen(
        argv, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True, env=env,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
        return proc.returncode, stdout, stderr
    except subprocess.TimeoutExpired:
        # SIGTERM first, SIGKILL only on refusal: hard-killing a client
        # mid-RPC is a plausible trigger for wedging the tunneled backend
        # for every subsequent client (both multi-hour chip-down records
        # start right after a SIGKILL mid-operation), and a clean client
        # shutdown costs only a few seconds of grace.
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
        stdout, stderr = "", ""
        try:
            # Keep whatever the child flushed before wedging — partial rows
            # from a short up-window are exactly the incremental progress
            # this watcher exists to bank, and the stderr BENCH_PHASE trail
            # is the only record of WHICH phase wedged.
            stdout, stderr = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                stdout, stderr = proc.communicate(timeout=15)
            except subprocess.TimeoutExpired:
                pass  # D-state child: abandon it
        return None, stdout or "", ("timeout after %ds | %s" % (timeout, (stderr or "").strip()[-700:]))


def probe(timeout=100):
    rc, out, err = _run_guarded([sys.executable, "-c", PROBE_CODE], timeout)
    if rc != 0 or "PROBE_OK" not in out:
        return False
    # The platform string matters: with the accelerator plugin absent (or an
    # ambient JAX_PLATFORMS=cpu) the matmul happily succeeds on CPU and the
    # watcher would burn every stage on the wrong backend and retire them.
    for line in out.splitlines():
        if line.startswith("PROBE_OK"):
            return line.strip().split()[-1] == "tpu"
    return False


def run_stage(name, argv, timeout, extra_env=None):
    t0 = time.time()
    env = None
    if extra_env:
        env = dict(os.environ)
        env.update(extra_env)
    rc, out, err = _run_guarded(argv, timeout, env=env)
    lines = []
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                lines.append(json.loads(line))
            except ValueError:
                pass
    # stderr tail recorded on EVERY outcome: a stage can exit 0 yet carry
    # only a CPU fallback, and its BENCH_PHASE trail (which phase the TPU
    # attempt wedged in) is then the only diagnostic that exists.
    _log({
        "stage": name, "rc": rc, "elapsed_s": round(time.time() - t0, 1),
        "results": lines, "stderr_tail": err.strip()[-900:],
    })
    # Retire only on a COMPLETE capture: at least one real TPU row and no
    # error rows.  A multi-config stage (train_configs --configs 2,2b,2c)
    # where one config succeeds and another times out must re-run next
    # window, or the failed configs are never captured; same for a
    # gar_kernels sweep with a failing tier.
    return (rc == 0 and any(_tpu_datum(r) for r in lines)
            and not any(r.get("error") for r in lines))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--once", action="store_true", help="one probe+pass, no loop")
    ap.add_argument("--stages", default=None, help="comma subset of stages")
    ap.add_argument("--sleep", type=int, default=600, help="seconds between probes")
    ap.add_argument("--fresh", action="store_true", help="forget completed stages")
    args = ap.parse_args()

    stages = _stages(sys.executable)
    if args.stages:
        keep = set(args.stages.split(","))
        stages = [s for s in stages if s[0] in keep]
    state = _load_state()
    if args.fresh:
        state = {"done": []}
        _save_state(state)
        # A fresh capture must also forget the children's per-cell resume
        # caches, or the "re-measured" stages would just reprint stale rows.
        for entry in stages:
            argv = entry[1]
            if "--resume-file" in argv:
                path = os.path.join(REPO, argv[argv.index("--resume-file") + 1])
                try:
                    os.remove(path)
                except OSError:
                    pass

    while True:
        todo = [s for s in stages if s[0] not in state["done"]]
        if not todo:
            _log({"event": "all-stages-complete"})
            return
        if probe():
            _log({"event": "chip-up", "todo": [s[0] for s in todo]})
            for name, argv, timeout, *extra in todo:
                if run_stage(name, argv, timeout, *(extra or [None])):
                    state["done"].append(name)
                    _save_state(state)
                else:
                    # A failed/timed-out stage usually means the chip wedged
                    # mid-pass — re-probe before burning another window.
                    if not probe():
                        _log({"event": "chip-wedged-mid-pass", "after": name})
                        break
        else:
            _log({"event": "chip-down"})
        if args.once:
            return
        time.sleep(args.sleep)


if __name__ == "__main__":
    main()

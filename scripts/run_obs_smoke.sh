#!/usr/bin/env bash
# Observability smoke on CPU (<60 s), docs/observability.md: one training
# run with an injected Byzantine worker under a TIME-VARYING chaos schedule,
# all three telemetry pillars on — then assert
#   1. the trace file parses as valid Chrome trace JSON (dispatch + host
#      spans present, run_id in the metadata),
#   2. the metrics surface scrapes in BOTH formats (training --metrics-file
#      Prometheus text round-trips the strict parser; the serve /metrics
#      endpoint negotiates JSON and Prometheus),
#   3. the forensics report NAMES the injected attacker (worker 0) over a
#      step range overlapping the attack window,
#   4. every summary JSONL line is stamped with the shared run_id.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_obs}"
run_id="obssmoke01"
rm -rf "$out"
mkdir -p "$out/sum"

JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment mnist --experiment-args batch-size:16 \
  --aggregator median --nb-workers 6 --nb-decl-byz-workers 1 \
  --nb-real-byz-workers 1 --chaos "0:calm 8:attack=empire,epsilon=4.0" \
  --max-step 24 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --summary-dir "$out/sum" --summary-delta 5 \
  --run-id "$run_id" \
  --trace-file "$out/run.trace.json" \
  --metrics-file "$out/train.prom" \
  --forensics "$out/forensics.json"

python - "$out" "$run_id" <<'EOF'
import json, os, sys

out, run_id = sys.argv[1], sys.argv[2]

# ---- pillar 1: Chrome trace JSON ------------------------------------- #
from aggregathor_tpu.obs.trace import validate_chrome_trace

payload = json.load(open(os.path.join(out, "run.trace.json")))
events = validate_chrome_trace(payload)
assert payload["otherData"]["run_id"] == run_id, payload["otherData"]
names = {e["name"] for e in events}
for wanted in ("train_step.dispatch", "input", "host_gap", "forensics.feed"):
    assert wanted in names, "missing span %r (got %r)" % (wanted, sorted(names))
dispatches = [e for e in events if e["name"] == "train_step.dispatch"]
assert len(dispatches) == 24, len(dispatches)
print("trace OK: %d events, %d dispatch spans, run_id %s"
      % (len(events), len(dispatches), run_id))

# ---- pillar 2a: training Prometheus dump ----------------------------- #
from aggregathor_tpu.obs.metrics import parse_prometheus

parsed = parse_prometheus(open(os.path.join(out, "train.prom")).read())
assert parsed["train_loss"]["type"] == "gauge"
steps = dict((n, v) for n, l, v in parsed["train_steps_total"]["samples"])
assert steps["train_steps_total"] == 24.0, steps
latency = parse_latency = parsed["train_step_latency_seconds"]
assert latency["type"] == "histogram"
count = [v for n, l, v in latency["samples"] if n.endswith("_count")]
assert count and count[0] >= 23, count  # first/compile dispatch excluded
workers = parsed["train_worker_sq_dist"]["samples"]
assert {l["worker"] for n, l, v in workers} == {str(w) for w in range(6)}
print("training exposition OK: %d families, %d steps counted"
      % (len(parsed), steps["train_steps_total"]))

# ---- pillar 3: forensics names the attacker -------------------------- #
report = json.load(open(os.path.join(out, "forensics.json")))
assert report["schema"] == "aggregathor.obs.forensics.v1", report["schema"]
assert report["run_id"] == run_id
assert report["suspects"] == [0], (
    "forensics named %r, expected the injected worker [0]" % report["suspects"])
intervals = report["workers"][0]["intervals"]
assert any(iv["end"] >= 9 for iv in intervals), intervals  # attack window
md = open(os.path.join(out, "forensics.md")).read()
assert "worker(s) 0" in md and "**BYZANTINE**" in md
print("forensics OK: named worker 0 over %s"
      % ["%d-%d" % (iv["start"], iv["end"]) for iv in intervals])

# ---- run_id joins the summary stream --------------------------------- #
sum_dir = os.path.join(out, "sum")
lines = [json.loads(line)
         for name in os.listdir(sum_dir)
         for line in open(os.path.join(sum_dir, name))]
assert lines and all(line.get("run_id") == run_id for line in lines), (
    "summary lines missing the run_id stamp")
print("summaries OK: %d lines stamped %s" % (len(lines), run_id))
EOF

# ---- pillar 2b: the serve /metrics endpoint in BOTH formats ---------- #
JAX_PLATFORMS=cpu python - <<'EOF'
import json, urllib.request

import jax

from aggregathor_tpu import models
from aggregathor_tpu.obs.metrics import parse_prometheus
from aggregathor_tpu.serve import InferenceEngine, InferenceServer

exp = models.instantiate("digits", ["batch-size:16"])
params = exp.init(jax.random.PRNGKey(0))
engine = InferenceEngine(exp, [params], max_batch=16)
server = InferenceServer(engine, port=0)
host, port = server.serve_background()
base = "http://%s:%d" % (host, port)
try:
    import numpy as np
    rows = np.zeros((3,) + engine.sample_shape, np.float32).tolist()
    req = urllib.request.Request(
        base + "/predict", json.dumps({"inputs": rows}).encode(),
        {"Content-Type": "application/json"})
    assert json.loads(urllib.request.urlopen(req, timeout=10).read())["predictions"]
    # JSON payload: byte-compatible keys the serve smoke scripts parse
    metrics = json.loads(urllib.request.urlopen(base + "/metrics", timeout=10).read())
    for key in ("queue_depth", "latency_ms", "served_rows", "compile_count"):
        assert key in metrics, (key, sorted(metrics))
    assert metrics["served_rows"] >= 3 and metrics["latency_ms"]["p95"] is not None
    # explicit ?format=prometheus
    text = urllib.request.urlopen(
        base + "/metrics?format=prometheus", timeout=10).read().decode()
    parsed = parse_prometheus(text)
    assert parsed["serve_request_latency_seconds"]["type"] == "histogram"
    served = dict((n, v) for n, l, v in parsed["serve_served_rows_total"]["samples"])
    assert served["serve_served_rows_total"] >= 3.0, served
    # Accept-header negotiation (what a Prometheus scraper sends)
    req = urllib.request.Request(
        base + "/metrics", headers={"Accept": "text/plain;version=0.0.4"})
    negotiated = urllib.request.urlopen(req, timeout=10).read().decode()
    parse_prometheus(negotiated)
    assert "serve_compile_count" in negotiated
    print("serve /metrics OK: JSON + %d Prometheus families, negotiation honored"
          % len(parsed))
finally:
    server.shutdown_all()
EOF

echo "obs smoke OK: $out"

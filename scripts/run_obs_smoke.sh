#!/usr/bin/env bash
# Observability smoke on CPU (<60 s), docs/observability.md: one training
# run with an injected Byzantine worker under a TIME-VARYING chaos schedule,
# all three telemetry pillars on — then assert
#   1. the trace file parses as valid Chrome trace JSON (dispatch + host
#      spans present, run_id in the metadata),
#   2. the metrics surface scrapes in BOTH formats (training --metrics-file
#      Prometheus text round-trips the strict parser; the serve /metrics
#      endpoint negotiates JSON and Prometheus),
#   3. the forensics report NAMES the injected attacker (worker 0) over a
#      step range overlapping the attack window,
#   4. every summary JSONL line is stamped with the shared run_id,
# then the FLEET leg (docs/observability.md "The control room"): a live
# training run + a live serving process federated through ONE
# FleetCollector scrape, the serve process killed mid-run and asserted
# `down` with its last sample HELD (fleet counter sums continuous), and
# the training run's causal journal round-tripped through load_journal.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_obs}"
run_id="obssmoke01"
rm -rf "$out"
mkdir -p "$out/sum"

JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment mnist --experiment-args batch-size:16 \
  --aggregator median --nb-workers 6 --nb-decl-byz-workers 1 \
  --nb-real-byz-workers 1 --chaos "0:calm 8:attack=empire,epsilon=4.0" \
  --max-step 24 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --summary-dir "$out/sum" --summary-delta 5 \
  --run-id "$run_id" \
  --trace-file "$out/run.trace.json" \
  --metrics-file "$out/train.prom" \
  --forensics "$out/forensics.json"

python - "$out" "$run_id" <<'EOF'
import json, os, sys

out, run_id = sys.argv[1], sys.argv[2]

# ---- pillar 1: Chrome trace JSON ------------------------------------- #
from aggregathor_tpu.obs.trace import validate_chrome_trace

payload = json.load(open(os.path.join(out, "run.trace.json")))
events = validate_chrome_trace(payload)
assert payload["otherData"]["run_id"] == run_id, payload["otherData"]
names = {e["name"] for e in events}
for wanted in ("train_step.dispatch", "input", "host_gap", "forensics.feed"):
    assert wanted in names, "missing span %r (got %r)" % (wanted, sorted(names))
dispatches = [e for e in events if e["name"] == "train_step.dispatch"]
assert len(dispatches) == 24, len(dispatches)
print("trace OK: %d events, %d dispatch spans, run_id %s"
      % (len(events), len(dispatches), run_id))

# ---- pillar 2a: training Prometheus dump ----------------------------- #
from aggregathor_tpu.obs.metrics import parse_prometheus

parsed = parse_prometheus(open(os.path.join(out, "train.prom")).read())
assert parsed["train_loss"]["type"] == "gauge"
steps = dict((n, v) for n, l, v in parsed["train_steps_total"]["samples"])
assert steps["train_steps_total"] == 24.0, steps
latency = parse_latency = parsed["train_step_latency_seconds"]
assert latency["type"] == "histogram"
count = [v for n, l, v in latency["samples"] if n.endswith("_count")]
assert count and count[0] >= 23, count  # first/compile dispatch excluded
workers = parsed["train_worker_sq_dist"]["samples"]
assert {l["worker"] for n, l, v in workers} == {str(w) for w in range(6)}
print("training exposition OK: %d families, %d steps counted"
      % (len(parsed), steps["train_steps_total"]))

# ---- pillar 3: forensics names the attacker -------------------------- #
report = json.load(open(os.path.join(out, "forensics.json")))
assert report["schema"] == "aggregathor.obs.forensics.v1", report["schema"]
assert report["run_id"] == run_id
assert report["suspects"] == [0], (
    "forensics named %r, expected the injected worker [0]" % report["suspects"])
intervals = report["workers"][0]["intervals"]
assert any(iv["end"] >= 9 for iv in intervals), intervals  # attack window
md = open(os.path.join(out, "forensics.md")).read()
assert "worker(s) 0" in md and "**BYZANTINE**" in md
print("forensics OK: named worker 0 over %s"
      % ["%d-%d" % (iv["start"], iv["end"]) for iv in intervals])

# ---- run_id joins the summary stream --------------------------------- #
sum_dir = os.path.join(out, "sum")
lines = [json.loads(line)
         for name in os.listdir(sum_dir)
         for line in open(os.path.join(sum_dir, name))]
assert lines and all(line.get("run_id") == run_id for line in lines), (
    "summary lines missing the run_id stamp")
print("summaries OK: %d lines stamped %s" % (len(lines), run_id))
EOF

# ---- pillar 2b: the serve /metrics endpoint in BOTH formats ---------- #
JAX_PLATFORMS=cpu python - <<'EOF'
import json, urllib.request

import jax

from aggregathor_tpu import models
from aggregathor_tpu.obs.metrics import parse_prometheus
from aggregathor_tpu.serve import InferenceEngine, InferenceServer

exp = models.instantiate("digits", ["batch-size:16"])
params = exp.init(jax.random.PRNGKey(0))
engine = InferenceEngine(exp, [params], max_batch=16)
server = InferenceServer(engine, port=0)
host, port = server.serve_background()
base = "http://%s:%d" % (host, port)
try:
    import numpy as np
    rows = np.zeros((3,) + engine.sample_shape, np.float32).tolist()
    req = urllib.request.Request(
        base + "/predict", json.dumps({"inputs": rows}).encode(),
        {"Content-Type": "application/json"})
    assert json.loads(urllib.request.urlopen(req, timeout=10).read())["predictions"]
    # bare /metrics serves Prometheus text on BOTH exporters since PR 16
    # (the training exporter always did; serve's historical JSON default
    # is retired) — one scrape config covers train + serve + router
    bare = urllib.request.urlopen(base + "/metrics", timeout=10).read().decode()
    assert "serve_compile_count" in bare and parse_prometheus(bare)
    # the JSON payload stays reachable through the EXPLICIT format
    metrics = json.loads(urllib.request.urlopen(
        base + "/metrics?format=json", timeout=10).read())
    for key in ("queue_depth", "latency_ms", "served_rows", "compile_count"):
        assert key in metrics, (key, sorted(metrics))
    assert metrics["served_rows"] >= 3 and metrics["latency_ms"]["p95"] is not None
    # explicit ?format=prometheus
    text = urllib.request.urlopen(
        base + "/metrics?format=prometheus", timeout=10).read().decode()
    parsed = parse_prometheus(text)
    assert parsed["serve_request_latency_seconds"]["type"] == "histogram"
    served = dict((n, v) for n, l, v in parsed["serve_served_rows_total"]["samples"])
    assert served["serve_served_rows_total"] >= 3.0, served
    # Accept-header negotiation (what a Prometheus scraper sends)
    req = urllib.request.Request(
        base + "/metrics", headers={"Accept": "text/plain;version=0.0.4"})
    negotiated = urllib.request.urlopen(req, timeout=10).read().decode()
    parse_prometheus(negotiated)
    assert "serve_compile_count" in negotiated
    print("serve /metrics OK: JSON + %d Prometheus families, negotiation honored"
          % len(parsed))
finally:
    server.shutdown_all()
EOF

# ---- fleet leg: two live processes on ONE scrape ---------------------- #
# a quick checkpoint for the serving process
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:16 \
  --aggregator average --nb-workers 4 --nb-devices 1 \
  --max-step 20 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --checkpoint-dir "$out/ckpt" --checkpoint-delta 20 --checkpoint-period -1 \
  --summary-delta -1 --summary-period -1 >"$out/ckpt.log" 2>&1

# a LIVE training run: exporter + causal journal + bounded-wait rounds.
# The FIXED 0.4 s deadline (no controller: the adaptive window would
# correctly converge past the persistent straggler and finish the run
# before the fleet polls) keeps it alive at ~2.4 steps/s until the
# SIGTERM below — whose flush path writes run_end into the journal.
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:8 \
  --aggregator krum --nb-workers 4 --nb-decl-byz-workers 1 \
  --max-step 2000 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --step-deadline 0.4 \
  --straggler-stall 0.6 --chaos "0:straggle=1.0" --chaos-args straggle-workers:1 \
  --run-id "${run_id}-train" --journal "$out/train.journal.jsonl" \
  --live-port 0 --live-ready-file "$out/train.ready" \
  >"$out/train.log" 2>&1 &
train_pid=$!

# a LIVE serving process with its own journal
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.serve \
  --experiment digits --experiment-args batch-size:16 \
  --ckpt-dir "$out/ckpt" --replicas 1 --gar none \
  --max-batch 8 --lanes 1 \
  --port 0 --ready-file "$out/serve.ready" \
  --run-id "${run_id}-serve" --journal "$out/serve.journal.jsonl" \
  >"$out/serve.log" 2>&1 &
serve_pid=$!

for f in train.ready serve.ready; do
  for _ in $(seq 1 120); do [ -f "$out/$f" ] && break; sleep 0.5; done
  [ -f "$out/$f" ] || { echo "$f never appeared"; tail "$out"/*.log; exit 1; }
done
train_addr=$(cat "$out/train.ready")
read -r serve_host serve_port _serve_cli_pid < "$out/serve.ready"

# the one-scrape federation point over both processes
JAX_PLATFORMS=cpu python -m aggregathor_tpu.obs.fleet \
  --port 0 --ready-file "$out/fleet.ready" \
  --poll-interval 0.4 --down-after 2 \
  --instance "train=${train_addr// /:}" \
  --instance "serve=$serve_host:$serve_port" \
  --journal "train=$out/train.journal.jsonl" \
  --journal "serve=$out/serve.journal.jsonl" \
  >"$out/fleet.log" 2>&1 &
fleet_pid=$!
for _ in $(seq 1 60); do [ -f "$out/fleet.ready" ] && break; sleep 0.5; done
[ -f "$out/fleet.ready" ] || {
  echo "fleet collector never became ready"; tail "$out/fleet.log"
  kill -TERM "$train_pid" "$serve_pid" 2>/dev/null || true; exit 1
}
read -r fleet_host fleet_port _fleet_pid < "$out/fleet.ready"

python - "$out" "$fleet_host" "$fleet_port" "$serve_pid" <<'EOF'
import json, os, signal, sys, time, urllib.request

from aggregathor_tpu.obs.metrics import parse_prometheus

out, host, port, serve_pid = sys.argv[1], sys.argv[2], sys.argv[3], int(sys.argv[4])
base = "http://%s:%s" % (host, port)

def scrape():
    text = urllib.request.urlopen(base + "/fleet/metrics", timeout=10).read().decode()
    return parse_prometheus(text)

def series(parsed, family):
    return {l.get("instance"): v for _n, l, v in parsed[family]["samples"]}

# both instances up on one scrape, per-instance labels + fleet sums
parsed = None
for _ in range(100):
    candidate = scrape()
    up = series(candidate, "fleet_instance_up")
    if up.get("train") == 1.0 and up.get("serve") == 1.0:
        parsed = candidate
        break
    time.sleep(0.3)
assert parsed is not None, "both instances never read up on the fleet scrape"
assert "serve_queue_rows" in parsed, sorted(parsed)      # serve's family
assert "train_steps_total" in parsed, sorted(parsed)     # train's family
steps_before = series(parsed, "train_steps_total")
assert steps_before["_fleet"] >= 1.0, steps_before
served_family = "serve_served_rows_total"
served_before = series(parsed, served_family)
assert served_before.get("serve") is not None, sorted(parsed)
status = json.loads(urllib.request.urlopen(base + "/fleet/status", timeout=10).read())
assert status["instances"]["train"]["up"] and status["instances"]["serve"]["up"]
assert status["instances"]["train"]["status"]["step"] >= 1
print("fleet scrape OK: train step %s, both instances up"
      % status["instances"]["train"]["status"]["step"])

# real traffic, so the continuity assertion below guards a NONZERO sum
serve_url = status["instances"]["serve"]["url"]
rows = [[[[0.0]] * 8] * 8] * 3  # 3 x (8, 8, 1) digits inputs
req = urllib.request.Request(
    serve_url + "/predict", json.dumps({"inputs": rows}).encode(),
    {"Content-Type": "application/json"})
assert json.loads(urllib.request.urlopen(req, timeout=30).read())["predictions"]
for _ in range(100):
    parsed = scrape()
    served_before = series(parsed, served_family)
    if served_before.get("serve", 0.0) >= 3.0:
        break
    time.sleep(0.3)
assert served_before.get("serve", 0.0) >= 3.0, served_before

# kill the serve process mid-run: it must read DOWN with its last sample
# HELD — the fleet counter sums stay continuous, never jump backwards
os.kill(serve_pid, signal.SIGTERM)
down = None
for _ in range(100):
    candidate = scrape()
    up = series(candidate, "fleet_instance_up")
    if up.get("serve") == 0.0:
        down = candidate
        break
    time.sleep(0.3)
assert down is not None, "killed serve instance never read down"
stale = series(down, "fleet_instance_stale")
assert stale["serve"] == 1.0 and stale["train"] == 0.0, stale
served_after = series(down, served_family)
assert served_after["serve"] >= served_before["serve"] >= 3.0, (
    served_before, served_after)
assert served_after["_fleet"] >= served_before["_fleet"] >= 3.0, (
    served_before, served_after)
steps_after = series(down, "train_steps_total")
assert steps_after["_fleet"] >= steps_before["_fleet"], (steps_before, steps_after)
errors = series(down, "fleet_scrape_errors_total")
assert errors["serve"] >= 2.0, errors
print("down leg OK: serve down+stale, fleet sums continuous (%s -> %s)"
      % (served_before["_fleet"], served_after["_fleet"]))
EOF

# graceful stop: the runner's flush path writes run_end into the journal
kill -TERM "$train_pid"
wait "$train_pid" || { echo "training run failed"; tail "$out/train.log"; exit 1; }
wait "$serve_pid" 2>/dev/null || true

python - "$out" "$fleet_host" "$fleet_port" "$run_id" <<'EOF'
import json, os, sys, urllib.request

from aggregathor_tpu.obs import events

out, host, port, run_id = sys.argv[1], sys.argv[2], sys.argv[3], sys.argv[4]

# the run's journal round-trips through the validator
records = events.load_journal(os.path.join(out, "train.journal.jsonl"))
kinds = events.counts_by_type(records)
assert records[0]["type"] == "run_start" and records[-1]["type"] == "run_end"
assert kinds.get("bounded_round", 0) >= 1, kinds   # the stragglers journal
assert all(r["run_id"] == run_id + "-train" for r in records)
serve_records = events.load_journal(os.path.join(out, "serve.journal.jsonl"))
assert [r["type"] for r in serve_records][0] == "run_start"

# and the collector merges both timelines on one endpoint
merged = json.loads(urllib.request.urlopen(
    "http://%s:%s/fleet/journal" % (host, port), timeout=10).read())
assert merged["schema"] == events.SCHEMA
assert merged["instances"]["train"]["events"] == len(records)
instances = {r["instance"] for r in merged["events"]}
assert instances == {"train", "serve"}, instances
print("journal OK: %d train event(s) %s, %d serve event(s), one merged timeline"
      % (len(records), dict(kinds), len(serve_records)))
EOF

kill -TERM "$fleet_pid" 2>/dev/null || true
wait "$fleet_pid" 2>/dev/null || true

echo "obs smoke OK: $out"

#!/usr/bin/env bash
# Compressed-exchange smoke on CPU (<45 s; docs/engine.md "The wire").
# (Leg 1) one real-CLI --exchange int8:ef run asserting (1) finite loss
# through every summary, (2) nonzero bytes_on_wire_total with
# exchange_compression_ratio >= 3.5 vs the f32 wire on the one metrics
# registry, (3) the EF buffer serialized beside the snapshot (a resumed
# run restores the residual, not zeros).  (Leg 2) the
# aggregathor.compress.sweep.v1 schema round-trips on the checked-in
# COMPRESS_r14.json and its verdict still reads PASS.  (Leg 3) the
# graftcheck GAR-contract int8 probe (GC005): a registered rule that
# breaks under the quantized wire is a GC finding, not a surprise — the
# core rules must probe clean here.
# The CI-sized version of benchmarks/compress_sweep.py.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_compress}"
rm -rf "$out"
mkdir -p "$out"

# ---- leg 1: int8:ef through the real CLI ----------------------------- #
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:8 \
  --aggregator krum --nb-workers 8 --nb-decl-byz-workers 2 \
  --max-step 12 --platform cpu --learning-rate-args initial-rate:0.05 \
  --exchange int8:ef \
  --checkpoint-dir "$out/ckpt" --checkpoint-delta 6 \
  --evaluation-delta 0 --summary-delta 4 \
  --metrics-file "$out/metrics.prom" \
  --summary-dir "$out/summaries"

python - "$out" <<'EOF'
import glob, json, os, sys

import numpy as np

out = sys.argv[1]

# (1) finite loss all the way
losses = []
for path in glob.glob(os.path.join(out, "summaries", "*.jsonl")):
    for line in open(path):
        event = json.loads(line)
        if "total_loss" in event:
            losses.append(float(event["total_loss"]))
assert losses and np.isfinite(losses).all(), losses

# (2) wire accounting on the one registry: 12 steps x 8 workers x
# (d + 4) bytes, ratio >= 3.5 (int8 reads ~4.0 at this model size)
prom = open(os.path.join(out, "metrics.prom")).read()
def value(name):
    return [float(l.rsplit(" ", 1)[1]) for l in prom.splitlines()
            if l.startswith(name + " ")][0]
bytes_total = value("bytes_on_wire_total")
ratio = value("exchange_compression_ratio")
assert bytes_total > 0, prom
assert ratio >= 3.5, ratio

# (3) the EF residual is serialized state: the snapshot carries a
# nonzero 'ef' entry (checkpoint -> restore preserves it bit-exactly;
# tests/test_compress.py pins the full round-trip)
import flax.serialization
snaps = sorted(glob.glob(os.path.join(out, "ckpt", "*.ckpt")))
assert snaps, os.listdir(os.path.join(out, "ckpt"))
raw = flax.serialization.msgpack_restore(open(snaps[-1], "rb").read())
payload = raw.get("state", raw)
assert "ef" in payload, sorted(payload)
ef = np.asarray(list(payload["ef"].values())[0] if isinstance(payload["ef"], dict) else payload["ef"])
assert np.abs(ef).max() > 0, "serialized EF residual is all zeros"

print("compress smoke: CLI leg OK (%d summaries, %.0f bytes on wire, "
      "ratio %.2fx, EF serialized)" % (len(losses), bytes_total, ratio))
EOF

# ---- leg 2: sweep schema round-trip on the checked-in document ------- #
JAX_PLATFORMS=cpu python - <<'EOF'
import sys

sys.path.insert(0, "benchmarks")
import compress_sweep

doc = compress_sweep.load("COMPRESS_r14.json")
assert doc["verdict"]["pass"], doc["verdict"]
assert doc["incremental"]["overlap_fraction"] > 0
print("compress smoke: schema leg OK (%d cells, int8 ratio ok, "
      "overlap %.2f)" % (len(doc["cells"]),
                         doc["incremental"]["overlap_fraction"]))
EOF

# ---- leg 3: the graftcheck int8-wire probe (GC005) ------------------- #
JAX_PLATFORMS=cpu python - <<'EOF'
from aggregathor_tpu.analysis import gar_contract

for spec in ("krum", "average", "median", "bucketing:s=2,inner=krum"):
    findings = gar_contract.check_spec(spec)
    assert not findings, (spec, [str(f) for f in findings])
print("compress smoke: GC005 leg OK (core rules survive the int8 wire)")
EOF

echo "compress smoke: ALL OK -> $out"

#!/usr/bin/env bash
# Input-pipeline smoke on CPU (~30 s), docs/input_pipeline.md: one short
# training per input source — host-sync (--prefetch 0), pipelined
# (ChunkPipeline: sharded ping-pong gather, sliced transfer, device
# assemble) and device-resident (--input-source device, tail included) —
# then assert
#   1. sync and pipelined runs reach the IDENTICAL final loss (the
#      pipeline is a transport change, not a stream change),
#   2. the pipelined run's metrics registry shows every chunk produced
#      and a NONZERO input_overlap_fraction (overlap measured, not
#      presumed),
#   3. the device run covers every step device-sampled (3 chunks + a
#      2-step tail through the tail executable; no host-batch fallback),
#   4. benchmarks/input_pipeline.py emits a valid
#      aggregathor.input.pipeline.v1 document with bit-identical final
#      losses across its host modes.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_input}"
rm -rf "$out"
mkdir -p "$out"

common=(--experiment digits --experiment-args batch-size:16
        --aggregator average --nb-workers 4 --max-step 14 --unroll 4
        --learning-rate-args initial-rate:0.05 --seed 1
        --evaluation-delta -1 --evaluation-period -1)

# 1/3: host-sync (input on the dispatch path)
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner "${common[@]}" \
  --prefetch 0 --metrics-file "$out/sync.prom"

# 2/3: pipelined (3 chunks through the ChunkPipeline, then a 2-step tail)
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner "${common[@]}" \
  --prefetch 2 --input-slices 2 --metrics-file "$out/pipeline.prom"

# 3/3: device-resident sampling, tail executable included
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner "${common[@]}" \
  --input-source device --prefetch 0 --metrics-file "$out/device.prom"

# 4: the benchmark document (schema + per-mode loss identity)
JAX_PLATFORMS=cpu python benchmarks/input_pipeline.py \
  --experiment digits --experiment-args batch-size:16 --gar average --f 0 \
  --nb-workers 4 --unroll 4 --chunks 3 --slices 2 \
  --output "$out/input_pipeline.json"

python - "$out" <<'EOF'
import json, math, os, sys

from aggregathor_tpu.obs.metrics import parse_prometheus

out = sys.argv[1]

def gauge(parsed, family):
    assert family in parsed, "missing %r (got %r)" % (family, sorted(parsed))
    return dict((n, v) for n, l, v in parsed[family]["samples"])[family]

sync = parse_prometheus(open(os.path.join(out, "sync.prom")).read())
pipe = parse_prometheus(open(os.path.join(out, "pipeline.prom")).read())
dev = parse_prometheus(open(os.path.join(out, "device.prom")).read())

# ---- 1: the pipeline changes transport, never the trajectory ---------- #
loss_sync, loss_pipe = gauge(sync, "train_loss"), gauge(pipe, "train_loss")
assert loss_sync == loss_pipe, (
    "pipelined input diverged from sync: %r vs %r" % (loss_pipe, loss_sync))
assert gauge(sync, "train_steps_total") == 14.0
assert gauge(pipe, "train_steps_total") == 14.0
print("loss identity OK: sync == pipelined == %g over 14 steps" % loss_sync)

# ---- 2: overlap measured through the registry ------------------------- #
assert "input_chunks_total" not in sync, "sync run must not build a pipeline"
chunks = gauge(pipe, "input_chunks_total")
assert chunks == 3.0, "expected 3 pipelined chunks (14 steps, unroll 4): %r" % chunks
overlap = gauge(pipe, "input_overlap_fraction")
assert 0.0 < overlap <= 1.0, "overlap fraction not live: %r" % overlap
assert gauge(pipe, "input_wait_seconds_total") >= 0.0
assert gauge(pipe, "input_queue_depth") == 0.0  # drained at exit
print("overlap OK: %d chunks, overlap fraction %.3f" % (chunks, overlap))

# ---- 3: device run trained every step, loss finite -------------------- #
assert gauge(dev, "train_steps_total") == 14.0, "device tail steps missing"
loss_dev = gauge(dev, "train_loss")
assert math.isfinite(loss_dev), loss_dev
assert "input_chunks_total" not in dev, "device run must not gather on host"
print("device source OK: 14/14 steps device-sampled, final loss %g" % loss_dev)

# ---- 4: benchmark schema ---------------------------------------------- #
doc = json.load(open(os.path.join(out, "input_pipeline.json")))
assert doc["schema"] == "aggregathor.input.pipeline.v1", doc["schema"]
for key in ("experiment", "platform", "nb_workers", "gar", "f", "unroll",
            "chunks", "slices", "depth", "batch_size", "modes",
            "speedup_vs_sync", "bar"):
    assert key in doc, "schema missing %r" % key
assert set(doc["modes"]) == {"sync", "prefetch", "pipeline"}
for mode, row in doc["modes"].items():
    for key in ("steps_per_s", "input_gap_fraction", "final_loss", "timed_steps"):
        assert key in row, "mode %r missing %r" % (mode, key)
    assert row["steps_per_s"] > 0.0
losses = {row["final_loss"] for row in doc["modes"].values()}
assert len(losses) == 1, "host modes diverged: %r" % doc["modes"]
for key in ("overlap_fraction", "gather_s", "put_s", "wait_s", "chunks_produced"):
    assert key in doc["modes"]["pipeline"], key
assert set(doc["speedup_vs_sync"]) == {"prefetch", "pipeline"}
print("benchmark schema OK: %s, host modes loss-identical at %g"
      % (doc["schema"], losses.pop()))
EOF

echo "input smoke OK: $out"

#!/usr/bin/env bash
# Fleet traffic-plane smoke on CPU (<60 s): the PR-16 story end to end
# through the real CLIs (docs/serving.md "The traffic plane").
#
#   1. train a tiny digits model -> checkpoint stream (steps 20, 40)
#   2. fleet: TWO cli.serve backends following the same directory, ONE
#      cli.router admission port in front (real processes, real HTTP)
#   3. traffic leg: sticky closed-loop clients through the router; one
#      backend SIGKILLed MID-RUN -> zero dropped requests, zero
#      weights_step regressions per client
#   4. swap leg: extend training -> the surviving backend hot-swaps; the
#      router's step pin follows, responses serve the new step
#   5. scrape leg: a FleetCollector scrapes the ROUTER like any other
#      instance (bare /metrics is Prometheus since PR 16)
#   6. journal leg: the router journal replays the causal kill chain
#      (router_backend_down -> router_retry/route) and EV001-clean types
#   7. drain leg: SIGTERM on the surviving backend exits cleanly through
#      the drain path (serve_drain journaled)
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_fleet_smoke}"
rm -rf "$out"
mkdir -p "$out"

# ---- 1. train -> checkpoint stream (steps 20, 40)
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:16 \
  --aggregator average --nb-workers 4 --nb-devices 1 \
  --max-step 40 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --checkpoint-dir "$out/ckpt" --checkpoint-delta 20 --checkpoint-period -1 \
  --summary-delta -1 --summary-period -1

# ---- 2. the fleet: two backends + the router, all real processes
start_backend() {
  JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.serve \
    --experiment digits --experiment-args batch-size:16 \
    --ckpt-dir "$out/ckpt" --replicas 1 --gar none \
    --max-batch 8 --queue-bound 256 --lanes 2 \
    --follow --follow-interval 0.3 --drain-timeout 10 \
    --port 0 --ready-file "$out/ready_$1" \
    --journal "$out/journal_$1.jsonl" --run-id "smoke-$1" \
    > "$out/log_$1.txt" 2>&1 &
  echo $!
}
pid_a=$(start_backend a)
pid_b=$(start_backend b)
trap 'kill -9 "$pid_a" "$pid_b" "$router_pid" 2>/dev/null || true' EXIT
for _ in $(seq 1 90); do
  [ -f "$out/ready_a" ] && [ -f "$out/ready_b" ] && break; sleep 1
done
[ -f "$out/ready_a" ] && [ -f "$out/ready_b" ] || {
  echo "backends never became ready"; exit 1; }
addr_a=$(awk '{print $1 ":" $2}' "$out/ready_a")
addr_b=$(awk '{print $1 ":" $2}' "$out/ready_b")

JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.router \
  --backend "a=$addr_a" --backend "b=$addr_b" \
  --port 0 --ready-file "$out/ready_router" --poll-interval 0.1 \
  --down-after 2 --journal "$out/journal_router.jsonl" \
  --run-id smoke-router &
router_pid=$!
for _ in $(seq 1 30); do [ -f "$out/ready_router" ] && break; sleep 0.5; done
[ -f "$out/ready_router" ] || { echo "router never became ready"; exit 1; }

# ---- 3+4+5. traffic with a mid-run kill, the swap, the router scrape
JAX_PLATFORMS=cpu python - "$out" "$pid_b" <<'EOF'
import json, os, signal, sys, threading, time, urllib.error, urllib.request

out, victim_pid = sys.argv[1], int(sys.argv[2])
host, port, _pid = open("%s/ready_router" % out).read().split()
base = "http://%s:%s" % (host, port)
body = json.dumps({"inputs": [[0.0] * 64] * 4}).encode()

counts = {"ok": 0, "shed": 0, "dropped": 0}
steps = {}
lock = threading.Lock()
stop_at = time.monotonic() + 3.0

def client(name):
    request = urllib.request.Request(
        base + "/predict", data=body,
        headers={"Content-Type": "application/json", "X-Client-Id": name})
    seq = []
    while time.monotonic() < stop_at:
        try:
            with urllib.request.urlopen(request, timeout=30) as response:
                code, payload = response.status, json.loads(response.read())
        except urllib.error.HTTPError as exc:
            code, payload = exc.code, {}
        except Exception:
            code, payload = -1, {}
        with lock:
            if code == 200:
                counts["ok"] += 1
                seq.append(payload.get("weights_step"))
            elif code == 429:
                counts["shed"] += 1
            else:
                counts["dropped"] += 1
    with lock:
        steps[name] = seq

threads = [threading.Thread(target=client, args=("c%d" % i,))
           for i in range(4)]
for thread in threads: thread.start()
time.sleep(0.8)
os.kill(victim_pid, signal.SIGKILL)   # one backend dies under live traffic
for thread in threads: thread.join()

assert counts["dropped"] == 0, (counts, "a mid-run kill dropped requests")
assert counts["ok"] > 0, counts
for name, seq in steps.items():
    assert all(a <= b for a, b in zip(seq, seq[1:])), (
        "client %s observed weights_step regress: %r" % (name, seq))
print("traffic leg OK: %d ok / %d shed / 0 dropped across the kill"
      % (counts["ok"], counts["shed"]))

# the router /status knows the fleet: a up, b down
with urllib.request.urlopen(base + "/status", timeout=10) as response:
    status = json.loads(response.read())
assert status["role"] == "router", status
deadline = time.monotonic() + 5.0
while status["backends"]["b"]["up"] and time.monotonic() < deadline:
    time.sleep(0.2)
    with urllib.request.urlopen(base + "/status", timeout=10) as response:
        status = json.loads(response.read())
assert status["backends"]["a"]["up"] and not status["backends"]["b"]["up"], status

# swap leg: extend training -> the survivor hot-swaps, the pin follows
os.system(
    "JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner"
    " --experiment digits --experiment-args batch-size:16"
    " --aggregator average --nb-workers 4 --nb-devices 1"
    " --max-step 60 --learning-rate-args initial-rate:0.05 --prefetch 0"
    " --evaluation-delta -1 --evaluation-period -1"
    " --checkpoint-dir %s/ckpt --checkpoint-delta 20 --checkpoint-period -1"
    " --summary-delta -1 --summary-period -1 > /dev/null" % out)
request = urllib.request.Request(
    base + "/predict", data=body,
    headers={"Content-Type": "application/json", "X-Client-Id": "c0"})
deadline = time.monotonic() + 20.0
served = None
while time.monotonic() < deadline:
    with urllib.request.urlopen(request, timeout=30) as response:
        served = json.loads(response.read())["weights_step"]
    if served == 60:
        break
    time.sleep(0.25)
assert served == 60, "router never served the swapped step (still %r)" % served
print("swap leg OK: weights_step 60 live through the router")

# scrape leg: the router is itself a fleet instance (PR-16 bare-Prometheus)
from aggregathor_tpu.obs.fleet import FleetCollector
fc = FleetCollector({"router": "%s:%s" % (host, port)})
fc.poll_once()
assert fc.instance_up("router")
scraped = fc.status_payload()["instances"]["router"]["status"]
assert scraped["role"] == "router" and scraped["backends"]["a"]["known_step"] == 60
print("scrape leg OK: FleetCollector reads the router like any instance")
EOF

# ---- 6. journal leg: the causal kill chain, typed and EV001-clean
kill "$router_pid"
for _ in $(seq 1 20); do kill -0 "$router_pid" 2>/dev/null || break; sleep 0.5; done
JAX_PLATFORMS=cpu python - "$out" <<'EOF'
import sys
from aggregathor_tpu.obs import events

out = sys.argv[1]
records = events.load_journal("%s/journal_router.jsonl" % out)
downs = [r for r in records
         if r["type"] == "router_backend_down" and r["backend"] == "b"]
moved = [r for r in records
         if r["type"] == "router_retry"
         or (r["type"] == "router_route" and r.get("reason") == "backend_down")]
assert downs, "no router_backend_down for the killed backend"
assert any(r["seq"] > downs[0]["seq"] for r in moved), (
    "journal does not replay the kill -> reroute chain")
assert records[0]["type"] == "run_start" and records[-1]["type"] == "run_end"
print("journal leg OK: kill -> reroute chain replays (%d records)"
      % len(records))
EOF

# ---- 7. drain leg: SIGTERM exits the survivor through the drain path
kill "$pid_a"
for _ in $(seq 1 30); do kill -0 "$pid_a" 2>/dev/null || break; sleep 0.5; done
if kill -0 "$pid_a" 2>/dev/null; then
  echo "backend ignored SIGTERM (drain wedged)"; exit 1
fi
JAX_PLATFORMS=cpu python - "$out" <<'EOF'
import sys
from aggregathor_tpu.obs import events

out = sys.argv[1]
records = events.load_journal("%s/journal_a.jsonl" % out)
drains = [r for r in records if r["type"] == "serve_drain"]
phases = [r["phase"] for r in drains]
assert phases == ["begin", "finished"], phases
assert drains[-1]["quiescent"] is True, drains[-1]
print("drain leg OK: serve_drain begin -> finished (quiescent)")
EOF
trap - EXIT

echo "fleet smoke PASSED"

"""Capture fixed-seed golden outputs of the training engines.

Run BEFORE an engine refactor to freeze the current numerics, then assert
the refactored engine reproduces them bit-exactly
(tests/test_engine.py::test_unified_engine_bit_identical_to_goldens).

Writes tests/data/golden_engine.json: per-step losses/grad norms as float
hex strings (lossless) and a SHA-256 over the final parameter bytes.
"""

import hashlib
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
import optax

from aggregathor_tpu import gars, models
from aggregathor_tpu.core import build_optimizer, build_schedule
from aggregathor_tpu.parallel import RobustEngine, attacks, make_mesh


def param_digest(state):
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()


def run_flat(granularity, secure=False, momentum=None, attack_name=None,
             worker_metrics=False, reputation_decay=None, nb_devices=2):
    n, f, r = 6, 1, (1 if attack_name else 0)
    exp = models.instantiate("digits", ["batch-size:8"])
    gar = gars.instantiate("krum", n, f)
    tx = build_optimizer("sgd", build_schedule("fixed", ["initial-rate:0.05"]))
    attack = attacks.instantiate(attack_name, n, r) if attack_name else None
    engine = RobustEngine(
        make_mesh(nb_workers=nb_devices), gar, n, nb_real_byz=r, attack=attack,
        worker_momentum=momentum, worker_metrics=worker_metrics,
        reputation_decay=reputation_decay, granularity=granularity,
        secure=secure,
    )
    step = engine.build_step(exp.loss, tx)
    state = engine.init_state(exp.init(jax.random.PRNGKey(42)), tx, seed=1)
    it = exp.make_train_iterator(n, seed=3)
    losses, norms = [], []
    for _ in range(4):
        state, m = step(state, engine.shard_batch(next(it)))
        losses.append(float(jax.device_get(m["total_loss"])).hex())
        norms.append(float(jax.device_get(m["grad_norm"])).hex())
    # one scanned chunk through build_multi_step on top
    multi = engine.build_multi_step(exp.loss, tx)
    chunk = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *[next(it) for _ in range(3)])
    state, many = multi(state, engine.shard_batches(chunk))
    losses += [float(v).hex() for v in np.asarray(jax.device_get(many["total_loss"]))]
    return {"losses": losses, "grad_norms": norms, "params_sha256": param_digest(state)}


def run_sharded(granularity, l1=None, l2=None, momentum=None, gar_name="krum",
                f=1, nb_workers=4):
    from aggregathor_tpu.models import transformer as tfm
    from aggregathor_tpu.parallel.sharded_engine import ShardedRobustEngine

    cfg = tfm.TransformerConfig(vocab_size=17, d_model=8, n_heads=2, n_layers=2)
    mesh = make_mesh(nb_workers=2, model_parallelism=2)
    gar = gars.instantiate(gar_name, nb_workers, f)
    eng = ShardedRobustEngine(
        mesh, gar, nb_workers=nb_workers, granularity=granularity,
        l1_regularize=l1, l2_regularize=l2, worker_momentum=momentum,
    )
    tx = optax.sgd(0.05)
    state = eng.init_state(
        lambda k: tfm.init_params(cfg, k, n_stages=1), tfm.param_specs(cfg), tx)
    loss_fn = tfm.make_pipeline_loss(cfg, n_stages=1, microbatches=1)
    step = eng.build_step(loss_fn, tx, state)
    rng = np.random.default_rng(0xA66)
    losses, norms = [], []
    for _ in range(3):
        batch = {
            "tokens": rng.integers(0, 17, size=(nb_workers, 2, 8)).astype(np.int32),
            "targets": rng.integers(0, 17, size=(nb_workers, 2, 8)).astype(np.int32),
        }
        state, m = step(state, eng.shard_batch(batch))
        losses.append(float(jax.device_get(m["total_loss"])).hex())
        norms.append(float(jax.device_get(m["grad_norm"])).hex())
    return {"losses": losses, "grad_norms": norms, "params_sha256": param_digest(state)}


def main():
    goldens = {
        "flat_vector_rich": run_flat(
            "vector", secure=True, momentum=0.9, attack_name="signflip",
            worker_metrics=True, reputation_decay=0.9),
        "flat_leaf": run_flat("leaf"),
        "sharded_layer": run_sharded("layer", l1=1e-4, l2=1e-4, momentum=0.9),
        "sharded_global": run_sharded("global"),
    }
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "tests", "data", "golden_engine.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as fd:
        json.dump(goldens, fd, indent=2, sort_keys=True)
    print("goldens -> %s" % out)
    for name, doc in goldens.items():
        print("  %s: %d losses, params %s..." % (
            name, len(doc["losses"]), doc["params_sha256"][:16]))


if __name__ == "__main__":
    main()

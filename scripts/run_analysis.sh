#!/usr/bin/env bash
# Static-analysis gate: ruff (lint + import sort) + the four graftcheck
# checkers, with a machine-readable report (docs/analysis.md).
#
#   scripts/run_analysis.sh            # run everything, report, exit status
#   scripts/run_analysis.sh --check    # explicit gate mode (same exit
#                                      # contract, named for pre-commit use)
#   REPORT=path.json scripts/run_analysis.sh   # choose the report path
#
# Exit nonzero on: any unbaselined graftcheck finding, any stale or
# unjustified baseline entry, any ruff violation (when ruff is present —
# the container this repo grows in does not ship it, so its absence is a
# SKIP, never a pass-by-crash; config lives in pyproject.toml).
# Budget: < 30 s CPU (measured ~20 s on the 1-core CI box, dominated by
# the GAR contract probes).

set -euo pipefail
cd "$(dirname "$0")/.."

REPORT="${REPORT:-$(mktemp /tmp/graftcheck_report.XXXXXX.json)}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== ruff (lint + import sort; pyproject.toml [tool.ruff]) =="
if command -v ruff >/dev/null 2>&1; then
    ruff check aggregathor_tpu tests benchmarks scripts bench.py
elif python -m ruff --version >/dev/null 2>&1; then
    python -m ruff check aggregathor_tpu tests benchmarks scripts bench.py
else
    echo "ruff not installed in this environment: SKIPPED" \
         "(pip install -e '.[lint]' to enable)"
fi

echo "== graftcheck: retrace + prng + concurrency + gar-contract + events =="
python -m aggregathor_tpu.analysis --check --json "$REPORT"

echo "== report schema round-trip (aggregathor.analysis.report.v1) =="
python - "$REPORT" <<'PYEOF'
import json, sys

from aggregathor_tpu.analysis.report import validate_report

doc = validate_report(json.load(open(sys.argv[1])))
print("report ok: %s — %d finding(s), clean=%s -> %s"
      % (doc["schema"], doc["counts"]["total"], doc["clean"], sys.argv[1]))
PYEOF

#!/usr/bin/env python3
"""Convert CIFAR-10 between the reference's TFRecord layout and cifar10.npz.

The reference expects slim's ``download_and_convert_cifar10.py`` output
(``cifar10_train.tfrecord`` / ``cifar10_test.tfrecord``) symlinked under
``experiments/datasets/cifar10`` (reference: README.md:190-195,
experiments/cnnet.py:115-146).  This framework prefers one ``cifar10.npz``
(keys x_train/y_train/x_test/y_test) under ``$AGGREGATHOR_DATA``; both
directions are supported so either artifact can seed the other::

  python3 scripts/convert_cifar10.py --from-tfrecords DIR --to-npz cifar10.npz
  python3 scripts/convert_cifar10.py --from-npz cifar10.npz --to-tfrecords DIR

No TensorFlow involved — see aggregathor_tpu/models/tfrecord.py.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from aggregathor_tpu.models import tfrecord  # noqa: E402


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--from-tfrecords", metavar="DIR", help="read slim TFRecord shards from DIR")
    parser.add_argument("--to-npz", metavar="FILE", help="write cifar10.npz to FILE")
    parser.add_argument("--from-npz", metavar="FILE", help="read cifar10.npz from FILE")
    parser.add_argument("--to-tfrecords", metavar="DIR", help="write slim TFRecord shards to DIR")
    args = parser.parse_args(argv)

    if args.from_tfrecords and args.to_npz:
        x_train, y_train = tfrecord.read_cifar10_split(args.from_tfrecords, "train")
        x_test, y_test = tfrecord.read_cifar10_split(args.from_tfrecords, "test")
        np.savez_compressed(args.to_npz, x_train=x_train, y_train=y_train,
                            x_test=x_test, y_test=y_test)
        print("wrote %s (%d train / %d test)" % (args.to_npz, len(y_train), len(y_test)))
    elif args.from_npz and args.to_tfrecords:
        data = np.load(args.from_npz)
        to_u8 = lambda x: np.clip(np.asarray(x, np.float64) * (255.0 if x.dtype.kind == "f" else 1.0), 0, 255).astype(np.uint8)
        for split, (x, y) in (("train", (data["x_train"], data["y_train"])),
                              ("test", (data["x_test"], data["y_test"]))):
            path = tfrecord.write_cifar10_split(args.to_tfrecords, split, to_u8(x), y.ravel())
            print("wrote %s (%d records)" % (path, len(y)))
    else:
        parser.error("pick one direction: --from-tfrecords + --to-npz, or --from-npz + --to-tfrecords")
    return 0


if __name__ == "__main__":
    sys.exit(main())

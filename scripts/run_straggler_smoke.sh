#!/usr/bin/env bash
# Bounded-wait straggler smoke on CPU (<60 s): one real-CLI run with an
# injected SEVERE straggler coalition under --step-deadline, then assert
# (1) the run finished with a finite loss, (2) the stragglers are NAMED in
# the forensics report (straggler_timeout evidence, NOT attributed
# Byzantine), (3) the registry's timeout counters moved, and (4) the
# straggler-sweep schema round-trips.  The CI-sized version of
# benchmarks/straggler_sweep.py (docs/engine.md, "Bounded-wait").
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_straggler}"
mkdir -p "$out"

# 2 persistent stragglers (stall 4x the deadline) inside the declared f=2
# budget, scheduled through the real chaos DSL -> host straggler model
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:8 \
  --aggregator krum --nb-workers 8 --nb-decl-byz-workers 2 \
  --max-step 12 --platform cpu --learning-rate-args initial-rate:0.05 \
  --step-deadline 0.2 --straggler-stall 0.8 \
  --chaos "0:straggle=1.0" --chaos-args straggle-workers:2 \
  --worker-metrics --evaluation-delta 0 --summary-delta 4 \
  --forensics "$out/forensics.json" \
  --metrics-file "$out/metrics.prom" \
  --summary-dir "$out/summaries"

python - "$out" <<'EOF'
import glob, json, os, sys

out = sys.argv[1]

# (1) finite loss all the way: every scalar summary's total_loss is finite
losses = []
for path in glob.glob(os.path.join(out, "summaries", "*.jsonl")):
    for line in open(path):
        event = json.loads(line)
        if "total_loss" in event:
            losses.append(float(event["total_loss"]))
assert losses, "no scalar summaries written"
assert all(l == l and abs(l) != float("inf") for l in losses), losses

# (2) the stragglers are named — as deadline offenders, not as Byzantine
report = json.load(open(os.path.join(out, "forensics.json")))
assert report["schema"] == "aggregathor.obs.forensics.v1"
assert report["stragglers"] == [0, 1], report["stragglers"]
assert report["suspects"] == [], report["suspects"]
for worker in (0, 1):
    ev = report["workers"][worker]["evidence"]
    assert ev.get("straggler_timeout", 0) > 0, ev
    assert "nan_row" not in ev, ev  # the timeout EXPLAINS the NaN row

# (3) nonzero timeout counters on the one metrics registry
prom = open(os.path.join(out, "metrics.prom")).read()
assert 'straggler_timeouts_total{worker="0"}' in prom, prom
assert "bounded_wait_rounds_total 12" in prom, prom
value = [float(l.rsplit(" ", 1)[1]) for l in prom.splitlines()
         if l.startswith('straggler_timeouts_total{worker="0"}')][0]
assert value >= 8, prom

print("straggler smoke: CLI run OK (%d summaries, stragglers named)"
      % len(losses))
EOF

# (4) the sweep schema round-trips on a micro sweep (2 severities)
JAX_PLATFORMS=cpu python benchmarks/straggler_sweep.py \
  --steps 5 --severities 0,0.6 --deadline 0.15 --out "$out/sweep.json"

python - "$out/sweep.json" <<'EOF'
import sys
sys.path.insert(0, "benchmarks")
from straggler_sweep import load

doc = load(sys.argv[1])  # validates the schema
assert doc["verdict"]["breakdown_holds"], doc["verdict"]
print("straggler smoke: sweep schema round-trips, verdict %s"
      % ("PASS" if doc["verdict"]["pass"] else "partial"))
EOF

echo "straggler smoke OK -> $out"

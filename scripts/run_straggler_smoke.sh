#!/usr/bin/env bash
# Bounded-wait straggler smoke on CPU: (leg 1, v1 protocol) one real-CLI
# run with an injected SEVERE straggler coalition under a fixed
# --step-deadline, then assert (1) the run finished with a finite loss,
# (2) the stragglers are NAMED in the forensics report (straggler_timeout
# evidence, NOT attributed Byzantine), (3) the registry's timeout counters
# moved.  (Leg 2, adaptive v2, <30 s CPU) the same coalition under the
# DEADLINE CONTROLLER with stale infill and heavy-tail jitter, asserting
# the window converged BELOW the fixed deadline, nonzero
# stale_infill_rows_total, and the stragglers still named.  (Leg 3,
# bounded-wait v3) the adaptive protocol + int8:ef wire + --stale-reweight
# under a persistent coalition, asserting finite decreasing loss and
# nonzero typed stale_reweight events on the --journal.  (Leg 4) the
# straggler-sweep v3 schema round-trips on a micro sweep.
# The CI-sized version of benchmarks/straggler_sweep.py (docs/engine.md).
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_straggler}"
rm -rf "$out"
mkdir -p "$out"

# ---- leg 1: fixed-deadline v1 protocol ------------------------------- #
# 2 persistent stragglers (stall 4x the deadline) inside the declared f=2
# budget, scheduled through the real chaos DSL -> host straggler model
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:8 \
  --aggregator krum --nb-workers 8 --nb-decl-byz-workers 2 \
  --max-step 12 --platform cpu --learning-rate-args initial-rate:0.05 \
  --step-deadline 0.2 --straggler-stall 0.8 \
  --chaos "0:straggle=1.0" --chaos-args straggle-workers:2 \
  --worker-metrics --evaluation-delta 0 --summary-delta 4 \
  --forensics "$out/forensics.json" \
  --metrics-file "$out/metrics.prom" \
  --summary-dir "$out/summaries"

python - "$out" <<'EOF'
import glob, json, os, sys

out = sys.argv[1]

# (1) finite loss all the way: every scalar summary's total_loss is finite
losses = []
for path in glob.glob(os.path.join(out, "summaries", "*.jsonl")):
    for line in open(path):
        event = json.loads(line)
        if "total_loss" in event:
            losses.append(float(event["total_loss"]))
assert losses, "no scalar summaries written"
assert all(l == l and abs(l) != float("inf") for l in losses), losses

# (2) the stragglers are named — as deadline offenders, not as Byzantine
report = json.load(open(os.path.join(out, "forensics.json")))
assert report["schema"] == "aggregathor.obs.forensics.v1"
assert report["stragglers"] == [0, 1], report["stragglers"]
assert report["suspects"] == [], report["suspects"]
for worker in (0, 1):
    ev = report["workers"][worker]["evidence"]
    assert ev.get("straggler_timeout", 0) > 0, ev
    assert "nan_row" not in ev, ev  # the timeout EXPLAINS the NaN row

# (3) nonzero timeout counters on the one metrics registry
prom = open(os.path.join(out, "metrics.prom")).read()
assert 'straggler_timeouts_total{worker="0"}' in prom, prom
assert "bounded_wait_rounds_total 12" in prom, prom
value = [float(l.rsplit(" ", 1)[1]) for l in prom.splitlines()
         if l.startswith('straggler_timeouts_total{worker="0"}')][0]
assert value >= 8, prom

print("straggler smoke: fixed-deadline leg OK (%d summaries, stragglers named)"
      % len(losses))
EOF

# ---- leg 2: adaptive controller + stale infill (bounded-wait v2) ------ #
# same coalition with heavy-tail jitter; the controller tracks the honest
# arrival percentile and must converge the window BELOW the fixed deadline
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:8 \
  --aggregator krum --nb-workers 8 --nb-decl-byz-workers 2 \
  --max-step 12 --platform cpu --learning-rate-args initial-rate:0.05 \
  --step-deadline 0.3 --straggler-stall 0.8 \
  --deadline-percentile 70 --deadline-floor 0.02 --deadline-ema 0.5 \
  --stale-infill --stale-max-age 6 \
  --chaos "0:straggle=1.0,jitter=0.8" --chaos-args straggle-workers:2 \
  --worker-metrics --evaluation-delta 0 --summary-delta 4 \
  --forensics "$out/forensics_adaptive.json" \
  --metrics-file "$out/metrics_adaptive.prom" \
  --summary-dir "$out/summaries_adaptive"

python - "$out" <<'EOF'
import glob, json, os, sys

out = sys.argv[1]

losses = []
for path in glob.glob(os.path.join(out, "summaries_adaptive", "*.jsonl")):
    for line in open(path):
        event = json.loads(line)
        if "total_loss" in event:
            losses.append(float(event["total_loss"]))
assert losses and all(l == l and abs(l) != float("inf") for l in losses), losses

prom = open(os.path.join(out, "metrics_adaptive.prom")).read()

def value(prefix):
    rows = [float(l.rsplit(" ", 1)[1]) for l in prom.splitlines()
            if l.startswith(prefix)]
    assert rows, "missing %r in the exposition" % prefix
    return rows[0]

# the controller converged the window BELOW the fixed 0.3 s deadline
window = value("deadline_controller_window_seconds")
assert 0.0 < window < 0.3, window
assert value("deadline_controller_at_ceiling") == 0.0

# stale infill happened and was counted per worker
assert value('stale_infill_rows_total{worker="0"}') > 0, prom

# arrival histogram lanes exist for the honest workers
assert 'bounded_wait_arrival_seconds_count{worker="7"}' in prom

# stragglers named; stale_infill evidence distinguishes late from Byzantine
report = json.load(open(os.path.join(out, "forensics_adaptive.json")))
assert report["stragglers"] == [0, 1], report["stragglers"]
assert report["suspects"] == [], report["suspects"]
ev = report["workers"][0]["evidence"]
assert ev.get("stale_infill", 0) > 0, ev

print("straggler smoke: adaptive leg OK (window %.3fs < 0.3s fixed deadline)"
      % window)
EOF

# ---- leg 3: age-reweighted stale correction (bounded-wait v3) --------- #
# the adaptive protocol + compressed wire + --stale-reweight under the
# same persistent coalition: stale carries re-enter DAMPED by c(a) =
# 1/(1+a), each re-entry a typed stale_reweight event on the journal
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:8 \
  --aggregator krum --nb-workers 8 --nb-decl-byz-workers 2 \
  --max-step 12 --platform cpu --learning-rate-args initial-rate:0.05 \
  --step-deadline 0.3 --straggler-stall 0.8 \
  --deadline-percentile 70 --deadline-floor 0.02 --deadline-ema 0.5 \
  --stale-infill --stale-max-age 6 --stale-reweight \
  --exchange int8:ef \
  --chaos "0:straggle=1.0" --chaos-args straggle-workers:2 \
  --evaluation-delta 0 --summary-delta 4 \
  --journal "$out/reweight.journal.jsonl" \
  --summary-dir "$out/summaries_reweight"

python - "$out" <<'EOF'
import glob, json, os, sys

out = sys.argv[1]

losses = []
for path in glob.glob(os.path.join(out, "summaries_reweight", "*.jsonl")):
    for line in open(path):
        event = json.loads(line)
        if "total_loss" in event:
            losses.append(float(event["total_loss"]))
assert losses and all(l == l and abs(l) != float("inf") for l in losses), losses
assert losses[-1] < losses[0], losses  # damped carries still make progress

sys.path.insert(0, ".")
from aggregathor_tpu.obs import events

records = events.load_journal(os.path.join(out, "reweight.journal.jsonl"))
reweights = [r for r in records if r["type"] == "stale_reweight"]
assert reweights, "no stale_reweight events on the journal"
for rec in reweights:
    assert rec["worker"] in (0, 1), rec
    expected = 1.0 / (1.0 + rec["age"])
    assert abs(rec["coefficient"] - expected) < 1e-9, rec

print("straggler smoke: reweight leg OK (%d damped re-entries journaled)"
      % len(reweights))
EOF

# ---- leg 4: the sweep v3 schema round-trips on a micro sweep ---------- #
JAX_PLATFORMS=cpu python benchmarks/straggler_sweep.py \
  --steps 4 --rates 1.0 --gars average-nan --exchanges int8:ef --ages 4 \
  --ef-ages 4 --deadline 0.15 --stall 0.5 --skip-submesh \
  --out "$out/sweep.json" || true  # micro verdict may not PASS; schema must

python - "$out/sweep.json" <<'EOF'
import sys
sys.path.insert(0, "benchmarks")
from straggler_sweep import load

doc = load(sys.argv[1])  # validates the v3 schema
assert doc["verdict"]["breakdown_holds"], doc["verdict"]
assert any(c["arm"] == "reweight" and c["stale_total"] > 0
           for c in doc["cells"]), doc["cells"]
print("straggler smoke: sweep v3 schema round-trips, verdict %s"
      % ("PASS" if doc["verdict"]["pass"] else "partial"))
EOF

echo "straggler smoke OK -> $out"

"""On-silicon validation of the Pallas GAR kernel tier.

The Pallas kernels exist to replace the reference's C++ custom ops
(native/op_krum/cpu.cpp:53-122, native/op_bulyan/cpu.cpp:52-188), but the
CPU test suite exercises them only in interpreter mode
(ops/pallas_kernels.py auto-falls back off-TPU).  This script is the
missing piece of evidence: it REQUIRES a live TPU backend, runs every
``*-pallas`` rule COMPILED (non-interpret), cross-checks each output
against the jnp tier on-device, and times both tiers under the slope
protocol (timed section ends on a host fetch — ``block_until_ready`` is a
no-op under the tunneled backend, see BENCHMARKS.md).

Inputs include NaN-poisoned rows so the kernels' non-finite conventions
(+inf keying, lower-index ties, poison passthrough) are checked on silicon,
not just in the interpreter.

Usage::

    python scripts/pallas_tpu_check.py [--n 32] [--f 8] [--dims 65536,1048576]
                                       [--reps 10]

Prints one JSON line per (rule, d) with parity verdict + per-tier ms.
Exit code 0 iff every parity check passed.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Pin the plain rule names to the pure-jnp tier: round 4 made the base
# coordinate rules auto-dispatch to the Pallas kernels on TPU
# (gars/common.py use_pallas_coordinate_tier), which would silently turn
# this script's jnp column into a second Pallas column.  The *-pallas
# registrations override aggregate_block directly and ignore this.
os.environ["GRAFT_GAR_TIER"] = "jnp"


def time_fn(fn, sync, reps):
    """Amortized per-call ms, host-fetch synced (benchmarks/gar_kernels.py)."""
    sync(fn())  # warmup / compile + sync
    t0 = time.perf_counter()
    sync(fn())
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn()
    sync(out)
    t_many = time.perf_counter() - t0
    if reps > 1:
        return max(t_many - t_one, 0.0) / (reps - 1) * 1e3
    return t_many * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=32)
    ap.add_argument("--f", type=int, default=8)
    ap.add_argument("--dims", default="65536,1048576,8388608")
    ap.add_argument("--rules",
                    default="average-nan,median,averaged-median,krum,bulyan,trimmed-mean")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--nan-workers", type=int, default=2,
                    help="rows given scattered NaN coordinates (lossy-link parity)")
    ap.add_argument("--allow-interpret", action="store_true",
                    help="harness self-test: run off-TPU in interpreter mode "
                         "(timings meaningless; parity logic still exercised)")
    args = ap.parse_args()

    import jax

    env_platform = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if env_platform:
        # The env var alone is overridden by the ambient accelerator plugin;
        # the config-level pin wins (cli/runner.py does the same) — this is
        # what lets `JAX_PLATFORMS=cpu` exercise the exit-2 path off-TPU
        # without touching the possibly-wedged tunnel.
        jax.config.update("jax_platforms", env_platform)

    platform = jax.devices()[0].platform
    if platform != "tpu" and not args.allow_interpret:
        print(json.dumps({"error": "pallas_tpu_check requires a TPU backend, got %r" % platform}))
        sys.exit(2)

    from aggregathor_tpu import gars
    from aggregathor_tpu.ops import pallas_kernels as pk

    if platform == "tpu":
        assert not pk._interpret(), "on TPU the kernels must compile, not interpret"

    _first = jax.jit(lambda x: x.ravel()[0])

    def dev_sync(out):
        v = np.asarray(_first(out))  # host fetch = the only real sync here
        return float(v) if np.isfinite(v) else 0.0

    rng = np.random.default_rng(7)
    dims = [int(d) for d in args.dims.split(",")]
    failures = 0

    for d in dims:
        g_host = rng.normal(size=(args.n, d)).astype(np.float32)
        if args.nan_workers:
            # Scattered non-finite coordinates on the first k rows — the UDP
            # packet-loss shape the NaN conventions exist for
            # (reference mpi_rendezvous_mgr.patch:833-841).
            idx = rng.choice(d, size=max(8, d // 4096), replace=False)
            for w in range(args.nan_workers):
                g_host[w, idx[w::args.nan_workers]] = np.nan
        g_dev = jax.device_put(g_host)

        for rule in args.rules.split(","):
            f = min(args.f, (args.n - 3) // 4) if rule.startswith("bulyan") else args.f
            jgar = gars.instantiate(rule, args.n, f)
            pgar = gars.instantiate(rule + "-pallas", args.n, f)
            jagg = jax.jit(jgar.aggregate)
            pagg = jax.jit(pgar.aggregate)

            row = {"metric": "pallas_tpu_check", "rule": rule, "n": args.n,
                   "f": f, "d": d}
            try:
                out_p = np.asarray(pagg(g_dev))
                out_j = np.asarray(jagg(g_dev))
                # f32 pairwise distances over large d accumulate differently
                # between the Gram-form kernel and the jnp diff form; parity
                # is semantic (same selection, same coordinates) with a
                # float-accumulation tolerance.
                ok = bool(np.allclose(out_p, out_j, rtol=2e-3, atol=2e-4, equal_nan=True))
                if not ok:
                    bad = ~np.isclose(out_p, out_j, rtol=2e-3, atol=2e-4, equal_nan=True)
                    row["mismatch_count"] = int(bad.sum())
                    diffs = np.abs(out_p[bad] - out_j[bad])
                    finite = diffs[np.isfinite(diffs)]
                    # All-NaN diffs (poison-passthrough divergence) must not
                    # leak a bare NaN token into the JSONL (strict JSON).
                    row["max_abs_diff"] = float(finite.max()) if finite.size else None
                    row["nonfinite_mismatches"] = int(diffs.size - finite.size)
                row["parity"] = "ok" if ok else "FAIL"
                row["pallas_ms"] = round(time_fn(lambda: pagg(g_dev), dev_sync, args.reps), 4)
                row["jnp_tpu_ms"] = round(time_fn(lambda: jagg(g_dev), dev_sync, args.reps), 4)
                failures += 0 if ok else 1
            except Exception as exc:  # compile failure (VMEM/tiling) is a finding
                row["parity"] = "ERROR"
                row["error"] = "%s: %s" % (type(exc).__name__, str(exc)[:400])
                failures += 1
            print(json.dumps(row), flush=True)

    # Vmapped-kernel proof: the bucketed leaf path calls the rules under
    # jax.vmap (engine._aggregate_per_leaf_bucketed), which routes every
    # guarded kernel — coordinate median, averaged-median, trimmed-mean,
    # AND the streamed pairwise distances — through Pallas' batching rule:
    # exercised interpret-mode by the CPU suite, proven compiled here.
    # Green on ALL FOUR means the central vmap suspension
    # (gars/common.py _is_batched_tracer) can be lifted.
    beta = max(1, args.n - args.f)
    keep = max(1, args.n - 2 * args.f)
    vmap_cases = [
        ("median-vmap4", pk.coordinate_median),
        ("averaged-median-vmap4", lambda x: pk.coordinate_averaged_median(x, beta)),
        ("trimmed-mean-vmap4",
         lambda x: pk.coordinate_trimmed_mean(x, (args.n - keep) // 2, keep)),
        ("pairwise-dist-vmap4", pk.pairwise_sq_distances),
    ]
    for d in sorted(dims)[:2]:  # smallest two: the proof, not a sweep
        stack_host = rng.normal(size=(4, args.n, d)).astype(np.float32)
        stack_host[0, 0, :: max(1, d // 64)] = np.nan
        stack = jax.device_put(stack_host)
        for name, kernel in vmap_cases:
            row = {"metric": "pallas_tpu_check", "rule": name, "n": args.n,
                   "f": args.f, "d": d}
            try:
                vm = jax.jit(jax.vmap(kernel))
                out_v = np.asarray(vm(stack))
                out_l = np.stack([np.asarray(kernel(stack[i]))
                                  for i in range(stack.shape[0])])
                ok = bool(np.allclose(out_v, out_l, rtol=1e-6, atol=1e-6, equal_nan=True))
                row["parity"] = "ok" if ok else "FAIL"
                row["pallas_ms"] = round(time_fn(lambda: vm(stack), dev_sync, args.reps), 4)
                failures += 0 if ok else 1
            except Exception as exc:  # batching-rule lowering failure is a finding
                row["parity"] = "ERROR"
                row["error"] = "%s: %s" % (type(exc).__name__, str(exc)[:400])
                failures += 1
            print(json.dumps(row), flush=True)

    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    # TERM must unwind the interpreter so the backend client closes
    # cleanly — the capture watcher escalates TERM-before-KILL.
    from aggregathor_tpu.utils.proc import graceful_sigterm

    graceful_sigterm()
    main()

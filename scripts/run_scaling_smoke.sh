#!/usr/bin/env bash
# Large-n scaling smoke on CPU (<60 s), docs/gar_scaling.md: one n=64
# hierarchical-GAR training cell through the REAL CLI with the GAR cost
# probe on — then assert
#   1. the run finishes with a FINITE loss (every summary line),
#   2. the probe measured real work: gar_seconds_total > 0 on the metrics
#      registry (and the gar_probe_seconds gauge is populated),
#   3. a micro n-sweep through benchmarks/gar_kernels.py --sweep-ns writes
#      a document that round-trips the aggregathor.gar.scaling.v1 schema
#      contract (gars/scaling.py validate_scaling_doc).
# The sublinear-in-n² PERFORMANCE verdict is deliberately not gated here:
# at smoke scale (tiny d, two ns, one rep on a CI core) constants dominate
# the exponents — BENCHMARKS.md §2d is the measured claim.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_scaling}"
rm -rf "$out"
mkdir -p "$out/sum"

# 1+2: the n=64 hier:outer=krum cell (8 groups of 8; krum feasible at
# (8, 2)) with --gar-probe wiring gar.aggregate spans into the registry.
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment mnist --experiment-args batch-size:8 \
  --aggregator "hier:g=8,inner=median,outer=krum" \
  --nb-workers 64 --nb-decl-byz-workers 2 \
  --max-step 12 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --summary-dir "$out/sum" --summary-delta 4 \
  --gar-probe --metrics-file "$out/train.prom"

# 3: micro n-sweep through the real benchmark CLI (the verdict exit code
# is informational at this scale — schema validation below is the gate).
JAX_PLATFORMS=cpu python benchmarks/gar_kernels.py \
  --dims "" --rules "" --platform cpu \
  --sweep-ns 8,16 --sweep-d 256 --sweep-reps 1 \
  --sweep-out "$out/scaling.json" || true

python - "$out" <<'EOF'
import json, math, os, sys

out = sys.argv[1]

# ---- finite loss on every summary fire -------------------------------- #
sum_dir = os.path.join(out, "sum")
lines = [json.loads(line)
         for name in os.listdir(sum_dir)
         for line in open(os.path.join(sum_dir, name))]
losses = [line["total_loss"] for line in lines if "total_loss" in line]
assert losses, "no summary lines with total_loss"
assert all(math.isfinite(v) for v in losses), losses
print("loss OK: %d summary fires, final %.4f" % (len(losses), losses[-1]))

# ---- the probe measured real GAR work --------------------------------- #
from aggregathor_tpu.obs.metrics import parse_prometheus

parsed = parse_prometheus(open(os.path.join(out, "train.prom")).read())
total = dict((n, v) for n, l, v in parsed["gar_seconds_total"]["samples"])
assert total["gar_seconds_total"] > 0.0, total
gauge = dict((n, v) for n, l, v in parsed["gar_probe_seconds"]["samples"])
assert gauge["gar_probe_seconds"] > 0.0, gauge
gar_fires = [line["gar_seconds"] for line in lines if "gar_seconds" in line]
assert gar_fires and all(v > 0 for v in gar_fires), gar_fires
print("gar probe OK: %d fires, %.3f s cumulative (last %.3f s)"
      % (len(gar_fires), total["gar_seconds_total"], gauge["gar_probe_seconds"]))

# ---- the scaling document honors the schema contract ------------------ #
from aggregathor_tpu.gars.scaling import SCHEMA, validate_scaling_doc

doc = validate_scaling_doc(json.load(open(os.path.join(out, "scaling.json"))))
kinds = {e["kind"] for e in doc["rules"]}
assert kinds == {"flat", "composite"}, kinds
print("schema OK: %s — %d rules over ns=%s on %s"
      % (SCHEMA, len(doc["rules"]), doc["ns"], doc["platform"]))
EOF

echo "scaling smoke OK: $out"

#!/usr/bin/env bash
# Causal-plane smoke on CPU (<30 s): the PR-19 story end to end through
# the real CLIs (docs/observability.md "The causal plane").
#
#   1. train a tiny digits model WITH a journal -> one checkpoint + the
#      trainer's own causal record
#   2. mini-fleet: TWO cli.serve backends + ONE cli.router admission
#      port, every process journaling (real processes, real HTTP)
#   3. causal-header leg: a /predict through the router comes back with
#      the routing decision's X-Causal-Id token echoed as causal_id —
#      the token parses and names a router_route event that exists
#   4. kill leg: SIGKILL the client's assigned backend -> the next
#      request survives on the other backend and its echoed token is
#      the reroute (reason backend_down) whose cause resolves, in the
#      router's own journal, to the router_backend_down the kill caused
#      (router_retry cites the same down event)
#   5. postmortem leg: cli.postmortem over ALL FOUR journals merges the
#      fleet along cause edges and the story closes — verdict PASS,
#      exit 0, nonzero cause edges, and the markdown story spells the
#      kill -> down -> reroute chain out loud
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_postmortem_smoke}"
rm -rf "$out"
mkdir -p "$out"

# ---- 1. train -> checkpoint + the trainer's journal
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:16 \
  --aggregator average --nb-workers 4 --nb-devices 1 \
  --max-step 10 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --checkpoint-dir "$out/ckpt" --checkpoint-delta 10 --checkpoint-period -1 \
  --summary-delta -1 --summary-period -1 \
  --journal "$out/journal_train.jsonl" --run-id pm-train

# ---- 2. the mini-fleet, every process journaling
start_backend() {
  JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.serve \
    --experiment digits --experiment-args batch-size:16 \
    --ckpt-dir "$out/ckpt" --replicas 1 --gar none \
    --max-batch 8 --queue-bound 256 --lanes 2 --drain-timeout 5 \
    --port 0 --ready-file "$out/ready_$1" \
    --journal "$out/journal_$1.jsonl" --run-id "pm-$1" \
    > "$out/log_$1.txt" 2>&1 &
  echo $!
}
pid_a=$(start_backend a)
pid_b=$(start_backend b)
trap 'kill -9 "$pid_a" "$pid_b" "$router_pid" 2>/dev/null || true' EXIT
for _ in $(seq 1 90); do
  [ -f "$out/ready_a" ] && [ -f "$out/ready_b" ] && break; sleep 0.5
done
[ -f "$out/ready_a" ] && [ -f "$out/ready_b" ] || {
  echo "backends never became ready"; exit 1; }
addr_a=$(awk '{print $1 ":" $2}' "$out/ready_a")
addr_b=$(awk '{print $1 ":" $2}' "$out/ready_b")

# a long --poll-interval on purpose: the DOWN judgment must come from the
# request-path transport failure (the event the reroute cites), not from
# the scrape loop winning the race
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.router \
  --backend "a=$addr_a" --backend "b=$addr_b" \
  --port 0 --ready-file "$out/ready_router" --poll-interval 5 \
  --down-after 100 --journal "$out/journal_router.jsonl" \
  --run-id pm-router > "$out/log_router.txt" 2>&1 &
router_pid=$!
for _ in $(seq 1 30); do [ -f "$out/ready_router" ] && break; sleep 0.5; done
[ -f "$out/ready_router" ] || { echo "router never became ready"; exit 1; }

# ---- 3+4. the causal header across the wire, then across a kill
JAX_PLATFORMS=cpu python - "$out" "$pid_a" "$pid_b" <<'EOF'
import json, os, signal, sys, time, urllib.request

from aggregathor_tpu.obs import events

out = sys.argv[1]
pids = {"a": int(sys.argv[2]), "b": int(sys.argv[3])}
host, port, _pid = open("%s/ready_router" % out).read().split()
base = "http://%s:%s" % (host, port)
body = json.dumps({"inputs": [[0.0] * 64] * 2}).encode()

def predict():
    request = urllib.request.Request(
        base + "/predict", data=body,
        headers={"Content-Type": "application/json", "X-Client-Id": "c0"})
    with urllib.request.urlopen(request, timeout=30) as response:
        return json.loads(response.read())

def router_journal():
    return events.load_journal("%s/journal_router.jsonl" % out)

# causal-header leg: the first answer carries the initial route's token
payload = predict()
token = payload.get("causal_id")
assert token, "no causal_id echoed through the fleet: %r" % payload
ref = events.parse_cause(token)
assert ref["instance"] == "router" and ref["run_id"] == "pm-router", ref
route = [r for r in router_journal()
         if r["type"] == "router_route" and r["seq"] == ref["seq"]]
assert route and route[0]["reason"] == "initial", (token, route)
routed = route[0]["backend"]
print("causal-header leg OK: token %s names the initial route to %r"
      % (token, routed))

# kill leg: the assigned backend dies; the reroute CITES the down event
os.kill(pids[routed], signal.SIGKILL)
time.sleep(0.3)
payload = predict()                   # transport failure -> retry -> 200
token = payload.get("causal_id")
assert token, "no causal_id echoed after the kill: %r" % payload
ref = events.parse_cause(token)
records = router_journal()
by_seq = {r["seq"]: r for r in records}
reroute = by_seq[ref["seq"]]
assert reroute["type"] == "router_route" and \
    reroute["reason"] == "backend_down", reroute
assert reroute["backend"] != routed, reroute
cause = reroute.get("cause")
assert cause and cause.get("instance") is None, (
    "the reroute cites nothing: %r" % reroute)
down = by_seq[cause["seq"]]
assert down["type"] == "router_backend_down" and \
    down["backend"] == routed, (reroute, down)
retries = [r for r in records if r["type"] == "router_retry"]
assert retries and retries[0]["cause"]["seq"] == down["seq"], retries
print("kill leg OK: reroute %s cites router_backend_down(%s); "
      "router_retry cites the same event" % (token, routed))
with open("%s/victim" % out, "w") as fd:
    fd.write(routed)
EOF
victim=$(cat "$out/victim")
survivor=$([ "$victim" = a ] && echo b || echo a)

# ---- graceful teardown so every journal closes with run_end
kill "$router_pid"
eval "kill \"\$pid_$survivor\""
for _ in $(seq 1 30); do
  kill -0 "$router_pid" 2>/dev/null || break; sleep 0.5
done

# ---- 5. the postmortem: four journals, one verified story, exit 0
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.postmortem \
  --journal "train=$out/journal_train.jsonl" \
  --journal "a=$out/journal_a.jsonl" \
  --journal "b=$out/journal_b.jsonl" \
  --journal "router=$out/journal_router.jsonl" \
  --report "$out/postmortem.json" --story "$out/postmortem.md" --quiet
JAX_PLATFORMS=cpu python - "$out" <<'EOF'
import json, sys

out = sys.argv[1]
with open("%s/postmortem.json" % out) as fd:
    report = json.load(fd)
assert report["schema"] == "aggregathor.obs.postmortem.v1", report["schema"]
assert report["verdict"] == "PASS", report["failing"]
assert report["edges_total"] >= 2, report["edges_total"]
assert set(report["instances"]) == {"train", "a", "b", "router"}
story = open("%s/postmortem.md" % out).read()
assert "because" in story and "router_backend_down" in story, (
    "the story does not spell the kill chain out: %r" % story[:400])
print("postmortem leg OK: PASS over %d event(s), %d cause edge(s)"
      % (report["events_total"], report["edges_total"]))
EOF
trap - EXIT

echo "postmortem smoke PASSED"

#!/usr/bin/env bash
# Self-driving-run smoke on CPU (<60 s): the PR-17 supervisor story end
# to end through the real CLIs (docs/operations.md).
#
#   1. train a tiny digits model under --secure -> custody-signed
#      checkpoint stream (steps 10, 20)
#   2. cli.supervise over a declarative fleet spec: one cli.serve
#      backend (ready-file handshake, journal) + one already-finished
#      trainer slot that owns the checkpoint stream and a sentinel
#      verdict path
#   3. restart leg: SIGKILL the backend -> the supervisor restarts it
#      (new pid in the ready file, supervisor_restart journaled with
#      its liveness evidence)
#   4. rollback leg: hand the trainer slot a REGRESS verdict -> the
#      supervisor restores the second-newest snapshot through the
#      chain of custody and discards the regressed tail
#      (supervisor_rollback journaled, citing the verdict's judged_at)
#   5. journal leg: the supervisor's own journal is EV001-clean and
#      replays the whole story in causal order
#   6. postmortem leg (PR 19): cli.postmortem merges the supervisor's
#      and the backend's journals along cause edges and the story
#      CLOSES — the respawned backend's run_start cites the
#      supervisor_restart (the --cause argv injection), the rollback
#      names its verdict, no dangling refs, exit 0
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_soak_smoke}"
rm -rf "$out"
mkdir -p "$out"
secret="smoke-session-secret"

# ---- 1. train -> custody-signed checkpoint stream (steps 10, 20)
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:16 \
  --aggregator average --nb-workers 4 --nb-devices 1 \
  --max-step 20 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --checkpoint-dir "$out/ckpt" --checkpoint-delta 10 --checkpoint-period -1 \
  --secure --session-secret "$secret" \
  --summary-delta -1 --summary-period -1

# ---- 2. the fleet spec: a live backend + the (finished) trainer slot
JAX_PLATFORMS=cpu python - "$out" "$secret" <<'EOF'
import json, sys

out, secret = sys.argv[1], sys.argv[2]
spec = {"instances": [
    {"name": "backend", "role": "serve",
     "argv": ["{python}", "-m", "aggregathor_tpu.cli.serve",
              "--experiment", "digits", "--experiment-args", "batch-size:16",
              "--ckpt-dir", "%s/ckpt" % out, "--replicas", "1",
              "--gar", "none", "--max-batch", "8", "--queue-bound", "256",
              "--lanes", "2", "--follow", "--follow-interval", "0.3",
              "--drain-timeout", "5", "--session-secret", secret,
              "--port", "0", "--ready-file", "%s/ready_backend" % out,
              "--journal", "%s/journal_backend.jsonl" % out,
              "--run-id", "smoke-backend"],
     "env": {"JAX_PLATFORMS": "cpu"},
     "ready_file": "ready_backend",
     "journal": "journal_backend.jsonl",
     "cause_flag": True,
     "log": "log_backend.txt"},
    {"name": "train", "role": "trainer",
     "argv": ["{python}", "-c", "import time; time.sleep(2)"],
     "verdict": "verdict_train.json",
     "checkpoint_dir": "ckpt",
     "session_secret": secret},
]}
with open("%s/fleet.json" % out, "w") as fd:
    json.dump(spec, fd, indent=1)
EOF

# ---- the supervisor itself, through the real CLI
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.supervise \
  --fleet "$out/fleet.json" --tick-interval 0.25 --down-after 2 \
  --supervisor-args patience:0.5 backoff:2 max-restarts:4 flap-window:5 \
  --ready-file "$out/ready_supervisor" \
  --journal "$out/journal_supervisor.jsonl" --run-id smoke-supervisor \
  > "$out/log_supervisor.txt" 2>&1 &
sup_pid=$!
trap 'kill -9 "$sup_pid" 2>/dev/null || true' EXIT
for _ in $(seq 1 120); do
  [ -f "$out/ready_supervisor" ] && [ -f "$out/ready_backend" ] && break
  kill -0 "$sup_pid" 2>/dev/null || { echo "supervisor died during startup";
    tail -5 "$out/log_supervisor.txt"; exit 1; }
  sleep 0.5
done
[ -f "$out/ready_backend" ] || { echo "backend never became ready"; exit 1; }

# ---- 3+4. the kill, the restart, the forced REGRESS, the rollback
JAX_PLATFORMS=cpu python - "$out" <<'EOF'
import json, os, signal, sys, time

out = sys.argv[1]
old_pid = int(open("%s/ready_backend" % out).read().split()[2])
os.kill(old_pid, signal.SIGKILL)

# restart leg: the supervisor notices the corpse, waits out its backoff
# grace, respawns — the ready-file handshake carries the new pid
deadline = time.monotonic() + 40.0
new_pid = None
while time.monotonic() < deadline:
    try:
        fields = open("%s/ready_backend" % out).read().split()
        if len(fields) == 3 and int(fields[2]) != old_pid:
            new_pid = int(fields[2])
            break
    except (OSError, ValueError):
        pass                          # removed pre-spawn / mid-write
    time.sleep(0.25)
assert new_pid is not None, "supervisor never restarted the killed backend"
os.kill(new_pid, 0)                   # the restarted process is alive
print("restart leg OK: backend pid %d -> %d across the SIGKILL"
      % (old_pid, new_pid))

# rollback leg: hand the trainer slot a sentinel REGRESS verdict
def steps():
    return sorted(int(name.split("-")[1].split(".")[0])
                  for name in os.listdir("%s/ckpt" % out)
                  if name.startswith("model-") and name.endswith(".ckpt"))

before = steps()                      # snapshot the stream pre-verdict
assert len(before) >= 2, "seed run left fewer than 2 snapshots: %r" % before
verdict = {
    "schema": "aggregathor.obs.slo.v1.verdict", "verdict": "REGRESS",
    "judged_at": 1234.5, "run_id": "smoke-train",
    "baseline_run_id": "smoke-baseline", "regressed": ["steps_per_s"],
    "checks": [{"metric": "steps_per_s", "baseline": 1e9, "tolerance": 0.1,
                "direction": "higher", "current": 1.0, "bound": 9e8,
                "status": "regressed"}],
}
tmp = "%s/verdict_train.json.tmp" % out
with open(tmp, "w") as fd:
    json.dump(verdict, fd)
os.replace(tmp, "%s/verdict_train.json" % out)

deadline = time.monotonic() + 30.0
while time.monotonic() < deadline and steps() != before[:-1]:
    time.sleep(0.25)
assert steps() == before[:-1], (
    "rollback never discarded the regressed tail (steps %r, want %r)"
    % (steps(), before[:-1]))
print("rollback leg OK: step %d discarded, custody-verified step %d kept"
      % (before[-1], before[-2]))
EOF

# ---- 5. journal leg: the supervisor's own causal record
kill "$sup_pid"
for _ in $(seq 1 40); do kill -0 "$sup_pid" 2>/dev/null || break; sleep 0.5; done
if kill -0 "$sup_pid" 2>/dev/null; then
  echo "supervisor ignored SIGTERM"; exit 1
fi
JAX_PLATFORMS=cpu python - "$out" <<'EOF'
import os, sys
from aggregathor_tpu.obs import events

out = sys.argv[1]
records = events.load_journal("%s/journal_supervisor.jsonl" % out)
assert records[0]["type"] == "run_start" and records[-1]["type"] == "run_end"
restarts = [r for r in records if r["type"] == "supervisor_restart"]
assert restarts and all(r["evidence"] for r in restarts), restarts
assert any(r["instance"] == "backend" for r in restarts), restarts
rollbacks = [r for r in records if r["type"] == "supervisor_rollback"]
assert len(rollbacks) == 1, rollbacks
roll = rollbacks[0]
remaining = sorted(int(name.split("-")[1].split(".")[0])
                   for name in os.listdir("%s/ckpt" % out)
                   if name.startswith("model-") and name.endswith(".ckpt"))
assert roll["instance"] == "train", roll
assert roll["restore_step"] == remaining[-1], (roll, remaining)
assert roll["discarded_steps"], roll
assert all(s > roll["restore_step"] for s in roll["discarded_steps"]), roll
assert roll["custody_verified"] is True, roll
assert roll["evidence"]["judged_at"] == 1234.5, (
    "rollback does not cite the verdict that ordered it: %r" % roll)
kills = [r["seq"] for r in restarts if r["instance"] == "backend"]
assert kills[0] < roll["seq"], "journal order lost the causal story"
print("journal leg OK: restart -> rollback replays in causal order "
      "(%d records)" % len(records))
EOF

# ---- 6. postmortem leg: the fleet's journals close as ONE story
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.postmortem \
  --journal "supervisor=$out/journal_supervisor.jsonl" \
  --journal "backend=$out/journal_backend.jsonl" \
  --report "$out/postmortem.json" --quiet
JAX_PLATFORMS=cpu python - "$out" <<'EOF'
import json, sys

out = sys.argv[1]
with open("%s/postmortem.json" % out) as fd:
    report = json.load(fd)
assert report["verdict"] == "PASS", report["failing"]
chains = {(c["kind"], c["action"]["type"]) for c in report["chains"]}
assert ("spawn", "supervisor_restart") in chains, (
    "the respawned backend's run_start does not cite its restart: %r"
    % (report["chains"],))
assert ("verdict_rollback", "supervisor_rollback") in chains, chains
print("postmortem leg OK: verdict PASS, %d event(s), %d cause edge(s), "
      "%d chain(s)" % (report["events_total"], report["edges_total"],
                       len(report["chains"])))
EOF
trap - EXIT

echo "soak smoke PASSED"

#!/usr/bin/env bash
# Topology smoke on CPU (<45 s; docs/topology.md).  (Leg 1) one real-CLI
# --topology run (in-graph tree GAR + host tree plane) with a chaos
# corrupt-agg fault forging sub-aggregator (1, 0)'s custody tag:
# (1) forensics NAMES "1.0" on the sub-aggregator surface and blames NO
# leaf worker, (2) the journal replays the causal per-level chain
# (topology_corruption_verdict -> topology_reconstruction, EV001-clean
# types), (3) the int8 inter-level link reads a >1 compression ratio on
# the one metrics registry and the corruption counter is nonzero,
# (4) training loss stays finite through every summary.  (Leg 2) the
# aggregathor.topology.sweep.v1 schema round-trips on the checked-in
# TOPO_r18.json and its verdict still reads PASS at n >= 256.  (Leg 3)
# the graftcheck GAR-contract sweep over the tree composite nestings
# (tree-of-composites AND tree-under-hier) probes clean.
# The CI-sized version of benchmarks/topology_sweep.py.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_topology}"
rm -rf "$out"
mkdir -p "$out"

# ---- leg 1: the tree through the real CLI, corrupted sub-aggregator -- #
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:8 \
  --aggregator tree \
  --topology "tree:g=4,rules=median>average-nan,link=int8,redundancy=2" \
  --nb-workers 8 --nb-decl-byz-workers 1 \
  --max-step 10 --platform cpu --learning-rate-args initial-rate:0.05 \
  --chaos "0:corrupt-agg=1.0" \
  --evaluation-delta 0 --summary-delta 4 \
  --metrics-file "$out/metrics.prom" \
  --summary-dir "$out/summaries" \
  --journal "$out/journal.jsonl" --run-id toposmoke01 \
  --forensics "$out/forensics.json"

python - "$out" <<'EOF'
import glob, json, os, sys

import numpy as np

out = sys.argv[1]

# (1) the forged sub-aggregator is NAMED as a tree node — and the blame
# stays off the leaf workers (naming, not laundering)
report = json.load(open(os.path.join(out, "forensics.json")))
assert report["corrupt_subaggregators"] == ["1.0"], report["corrupt_subaggregators"]
assert report["suspects"] == [], report["suspects"]
named = [r for r in report["sub_aggregators"]
         if (r["level"], r["unit"]) == (1, 0)]
assert named and named[0]["corrupt"], named
assert named[0]["evidence"].get("forgery", 0) > 0, named[0]["evidence"]
assert named[0]["evidence"].get("reconstructed", 0) > 0, named[0]["evidence"]

# (2) the journal replays the causal chain per step: the custody verdict
# on (1, 0), then the redundant shadow serving the reconstruction
from aggregathor_tpu.obs import events
records = events.load_journal(os.path.join(out, "journal.jsonl"))
verdicts = [r for r in records if r["type"] == "topology_corruption_verdict"]
recons = [r for r in records if r["type"] == "topology_reconstruction"]
assert verdicts and recons, sorted({r["type"] for r in records})
assert all((r["level"], r["unit"]) == (1, 0) for r in verdicts), verdicts[:2]
for rec in recons:
    assert (rec["level"], rec["unit"]) == (1, 0) and rec["shadow"] != rec["unit"], rec
steps = {r["step"] for r in verdicts}
assert steps == {r["step"] for r in recons}, (steps, recons[:2])
index = {(r["type"], r.get("step")): i for i, r in enumerate(records)
         if r["type"].startswith("topology_")}
for step in steps:
    assert index[("topology_corruption_verdict", step)] \
        < index[("topology_reconstruction", step)], step

# (3) inter-level wire accounting + the corruption counter on the one
# metrics registry
prom = open(os.path.join(out, "metrics.prom")).read()
def value(prefix):
    rows = [float(l.rsplit(" ", 1)[1]) for l in prom.splitlines()
            if l.startswith(prefix)]
    assert rows, prefix
    return sum(rows)
assert value("topology_link_compression_ratio ") > 1.0, prom
assert value("topology_corruptions_total") > 0, prom
assert value("topology_reconstructions_total") > 0, prom
assert value("topology_bytes_on_wire_total") > 0, prom

# (4) training converged through the faulted round: finite losses
losses = []
for path in glob.glob(os.path.join(out, "summaries", "*.jsonl")):
    for line in open(path):
        event = json.loads(line)
        if "total_loss" in event:
            losses.append(float(event["total_loss"]))
assert losses and np.isfinite(losses).all(), losses

print("topology smoke: CLI leg OK (corrupt 1.0 named, %d verdicts, "
      "%d reconstructions, %d summaries finite)"
      % (len(verdicts), len(recons), len(losses)))
EOF

# ---- leg 2: sweep schema round-trip on the checked-in document ------- #
JAX_PLATFORMS=cpu python - <<'EOF'
import sys

sys.path.insert(0, "benchmarks")
import topology_sweep

doc = topology_sweep.load("TOPO_r18.json")
assert doc["verdict"]["pass"], doc["verdict"]
assert doc["config"]["nb_workers"] >= 256
assert doc["forensics"]["corrupt_subaggregators"] == ["1.0"]
assert doc["forensics"]["workers_blamed"] == []
print("topology smoke: schema leg OK (n=%d, %d cells, named %s)"
      % (doc["config"]["nb_workers"], len(doc["cells"]),
         doc["forensics"]["corrupt_subaggregators"]))
EOF

# ---- leg 3: the graftcheck tree-nesting contract sweep --------------- #
JAX_PLATFORMS=cpu python - <<'EOF'
from aggregathor_tpu.analysis import gar_contract

for spec in ("tree",
             "tree:g=2x2,rules=median>median>average-nan",
             "tree:g=4,rules=bucketing(s=2,inner=median)>krum",
             "hier:g=2,inner=median,outer=tree(g=2,rules=median>average-nan)"):
    findings = gar_contract.check_spec(spec)
    assert not findings, (spec, [str(f) for f in findings])
print("topology smoke: contract leg OK (tree nestings probe clean)")
EOF

echo "topology smoke: ALL OK -> $out"

#!/usr/bin/env bash
# Micro resilience campaign on CPU (<60 s): 2 GARs x (calm + empire) plus the
# f-breakdown probe on the robust rule, then assert the resilience-matrix
# JSON schema.  This is the CI-sized version of the full campaign
# (docs/chaos.md).
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_campaign}"
mkdir -p "$out"

JAX_PLATFORMS=cpu python -m aggregathor_tpu.chaos.campaign \
  --experiment mnist --experiment-args batch-size:16 \
  --nb-workers 8 --nb-decl-byz-workers 2 --nb-real-byz-workers 2 \
  --gars average median --attacks empire,epsilon=4.0 \
  --nb-steps 25 --learning-rate 0.05 --breakdown \
  --output "$out/matrix.json" --report "$out/report.md"

python - "$out/matrix.json" <<'EOF'
import json, sys

matrix = json.load(open(sys.argv[1]))
assert matrix["schema"] == "aggregathor.chaos.resilience-matrix.v1", matrix.get("schema")
for key in ("experiment", "nb_workers", "declared_byz", "nb_steps", "cells", "breakdown"):
    assert key in matrix, "missing top-level key %r" % key
from aggregathor_tpu.chaos.campaign import CELL_KEYS
assert matrix["cells"], "empty cell grid"
for cell in matrix["cells"]:
    for key in CELL_KEYS:
        assert key in cell, "cell missing %r: %r" % (key, cell)
    assert isinstance(cell["losses"], list) and cell["losses"]
by = {(c["gar"], c["scenario"]): c for c in matrix["cells"]}
# the AggregaThor thesis, as data: the mean falls to the coalition, the
# robust rule does not
assert by[("median", "empire")]["converged"], by[("median", "empire")]
assert not by[("average", "empire")]["converged"], by[("average", "empire")]
assert by[("average", "calm")]["converged"], by[("average", "calm")]
# the empirical f-breakdown boundary: r=f holds, a Byzantine majority breaks
assert matrix["breakdown"], "breakdown probe produced no entries"
for entry in matrix["breakdown"]:
    assert entry["bound_holds"] is True, entry
print("resilience matrix OK: %d cells + %d breakdown probes, schema %s"
      % (len(matrix["cells"]), len(matrix["breakdown"]), matrix["schema"]))
EOF

echo "report: $out/report.md"

# ---- guardian smoke (<60 s): injected breakdown regime -> rollback ->
# recovery, asserted from the tagged summary events (docs/guardian.md).
# The inf coalition provably breaks plain average (breakdown point 0);
# the ladder escalates to median, which excludes the inf rows.
rm -rf "$out/guardian"
mkdir -p "$out/guardian"
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment mnist --experiment-args batch-size:16 \
  --aggregator average --nb-workers 8 --nb-decl-byz-workers 2 \
  --nb-real-byz-workers 2 --chaos "0:calm 8:attack=inf" \
  --guardian --guardian-args ladder:gar=median recover:5 \
  --max-step 30 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --checkpoint-dir "$out/guardian/ckpt" --checkpoint-delta 4 --checkpoint-period -1 \
  --summary-dir "$out/guardian/sum" --summary-delta 5

python - "$out/guardian/sum" <<'EOF'
import json, math, os, sys

sum_dir = sys.argv[1]
events = [json.loads(line)
          for name in os.listdir(sum_dir)
          for line in open(os.path.join(sum_dir, name))]
rollbacks = [e for e in events if e.get("event") == "guardian_rollback"]
escalations = [e for e in events if e.get("event") == "guardian_escalation"]
recoveries = [e for e in events if e.get("event") == "guardian_recovered"]
assert rollbacks, "no guardian_rollback event"
assert escalations, "no guardian_escalation event"
assert recoveries, "no guardian_recovered event"
scalars = [e for e in events if "total_loss" in e]
final = scalars[-1]["total_loss"]
assert final is not None and math.isfinite(final), final
first = scalars[0]["total_loss"]
assert final < first, (first, final)  # recovered AND still learning
print("guardian smoke OK: %d rollback(s), escalated via %r, final loss %.3f < first %.3f"
      % (len(rollbacks), escalations[0]["rung"], final, first))
EOF

#!/usr/bin/env bash
# Micro resilience campaign on CPU (<60 s): 2 GARs x (calm + empire) plus the
# f-breakdown probe on the robust rule, then assert the resilience-matrix
# JSON schema.  This is the CI-sized version of the full campaign
# (docs/chaos.md).
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_campaign}"
mkdir -p "$out"

JAX_PLATFORMS=cpu python -m aggregathor_tpu.chaos.campaign \
  --experiment mnist --experiment-args batch-size:16 \
  --nb-workers 8 --nb-decl-byz-workers 2 --nb-real-byz-workers 2 \
  --gars average median --attacks empire,epsilon=4.0 \
  --nb-steps 25 --learning-rate 0.05 --breakdown \
  --output "$out/matrix.json" --report "$out/report.md"

python - "$out/matrix.json" <<'EOF'
import json, sys

matrix = json.load(open(sys.argv[1]))
assert matrix["schema"] == "aggregathor.chaos.resilience-matrix.v1", matrix.get("schema")
for key in ("experiment", "nb_workers", "declared_byz", "nb_steps", "cells", "breakdown"):
    assert key in matrix, "missing top-level key %r" % key
from aggregathor_tpu.chaos.campaign import CELL_KEYS
assert matrix["cells"], "empty cell grid"
for cell in matrix["cells"]:
    for key in CELL_KEYS:
        assert key in cell, "cell missing %r: %r" % (key, cell)
    assert isinstance(cell["losses"], list) and cell["losses"]
by = {(c["gar"], c["scenario"]): c for c in matrix["cells"]}
# the AggregaThor thesis, as data: the mean falls to the coalition, the
# robust rule does not
assert by[("median", "empire")]["converged"], by[("median", "empire")]
assert not by[("average", "empire")]["converged"], by[("average", "empire")]
assert by[("average", "calm")]["converged"], by[("average", "calm")]
# the empirical f-breakdown boundary: r=f holds, a Byzantine majority breaks
assert matrix["breakdown"], "breakdown probe produced no entries"
for entry in matrix["breakdown"]:
    assert entry["bound_holds"] is True, entry
print("resilience matrix OK: %d cells + %d breakdown probes, schema %s"
      % (len(matrix["cells"]), len(matrix["breakdown"]), matrix["schema"]))
EOF

echo "report: $out/report.md"

#!/usr/bin/env bash
# Serving smoke on CPU (<60 s): train a tiny digits model through the real
# CLI runner, serve it with 3 replicas (one NaN-poisoned via the chaos
# tie-in) under the median vote, fire concurrent clients, and assert
# /healthz, a finite p95, a nonzero shed count under burst, and
# fault-masked predictions (served == clean baseline).  CI-sized version of
# docs/serving.md.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_serve_smoke}"
rm -rf "$out"
mkdir -p "$out"

# ---- 1. train -> checkpoint (the model the server will load)
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:16 \
  --aggregator average --nb-workers 4 --nb-devices 1 \
  --max-step 40 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --checkpoint-dir "$out/ckpt" --checkpoint-delta 20 --checkpoint-period -1 \
  --summary-delta -1 --summary-period -1

# ---- 2. serve it: 3 replicas, replica 2 NaN-poisoned, median vote.
# Tiny queue bound + slow deadline make the burst phase shed deterministically.
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.serve \
  --experiment digits --experiment-args batch-size:16 \
  --ckpt-dir "$out/ckpt" --replicas 3 --gar median --poison-replica 2:nan \
  --port 0 --ready-file "$out/ready" --summary-dir "$out/sum" \
  --max-batch 8 --max-latency-ms 100 --queue-bound 4 &
server_pid=$!
trap 'kill "$server_pid" 2>/dev/null || true' EXIT

for _ in $(seq 1 60); do [ -f "$out/ready" ] && break; sleep 1; done
[ -f "$out/ready" ] || { echo "server never became ready"; exit 1; }

# ---- 3. concurrent clients: burst (sheds) then calm (fault-masked answers)
JAX_PLATFORMS=cpu python - "$out" <<'EOF'
import json, sys, threading, urllib.error, urllib.request

import numpy as np

out = sys.argv[1]
host, port, _pid = open("%s/ready" % out).read().split()
base = "http://%s:%s" % (host, port)

def post(payload):
    req = urllib.request.Request(base + "/predict", data=json.dumps(payload).encode(),
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())

def get(path):
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return json.loads(r.read())

# the clean baseline the poisoned server must match (median masks the NaN)
import jax
jax.config.update("jax_platforms", "cpu")
from aggregathor_tpu import models
from aggregathor_tpu.core import build_optimizer, build_schedule
from aggregathor_tpu.serve import InferenceEngine, restore_params

experiment = models.instantiate("digits", ["batch-size:16"])
tx = build_optimizer("sgd", build_schedule("fixed", ["initial-rate:0.01"]))
params, step = restore_params(experiment, "%s/ckpt" % out, tx)
x = np.asarray(experiment.dataset.x_test[:8], np.float32)
clean = InferenceEngine(experiment, [params], max_batch=8).predict(x)["predictions"]

health = get("/healthz")
assert health["status"] == "ok", health
assert health["replicas"] == 3, health

# burst: 24 concurrent single-row posts against queue bound 4 -> sheds
codes = []
lock = threading.Lock()
row = x[0].tolist()
def fire():
    code, _ = post({"inputs": [row]})
    with lock:
        codes.append(code)
threads = [threading.Thread(target=fire) for _ in range(24)]
for t in threads: t.start()
for t in threads: t.join()
assert set(codes) <= {200, 429}, sorted(set(codes))
assert 429 in codes, "burst produced no shed (codes: %r)" % sorted(set(codes))

# calm phase: sequential requests all succeed with FAULT-MASKED predictions
code, served = post({"inputs": x.tolist()})
assert code == 200, (code, served)
assert served["predictions"] == [int(p) for p in clean], (
    "served predictions diverge from the clean baseline: %r vs %r"
    % (served["predictions"], list(clean)))
assert served["disagreement"][2] is None, served  # NaN replica -> null (inf)

metrics = get("/metrics")
assert metrics["shed_count"] > 0, metrics
p95 = metrics["latency_ms"]["p95"]
assert p95 is not None and np.isfinite(p95), metrics
assert metrics["suspect_replicas"] == [2], metrics
assert metrics["compile_count"] == metrics["nb_buckets"], metrics  # zero steady-state recompiles
print("serve smoke OK: step-%s checkpoint, %d sheds under burst, p95=%.1f ms, "
      "poisoned replica masked + flagged" % (step, metrics["shed_count"], p95))
EOF

# ---- 4. graceful shutdown (SIGTERM must not wedge the serve loop)
kill "$server_pid"
for _ in $(seq 1 20); do kill -0 "$server_pid" 2>/dev/null || break; sleep 0.5; done
if kill -0 "$server_pid" 2>/dev/null; then
  echo "server ignored SIGTERM"; kill -9 "$server_pid"; exit 1
fi
trap - EXIT

# the summary stream carries the serve events
python - "$out/sum" <<'EOF'
import json, os, sys
sum_dir = sys.argv[1]
events = [json.loads(line)
          for name in os.listdir(sum_dir)
          for line in open(os.path.join(sum_dir, name))]
batches = [e for e in events if e.get("event") == "serve_batch"]
sheds = [e for e in events if e.get("event") == "serve_shed"]
assert batches, "no serve_batch summary events"
assert sheds, "no serve_shed summary events"
print("summary stream OK: %d serve_batch + %d serve_shed event(s)"
      % (len(batches), len(sheds)))
EOF

echo "serve smoke PASSED"

#!/usr/bin/env bash
# Serving smoke on CPU (<60 s): the serve/ v2 story end to end through the
# real CLIs (docs/serving.md).
#
#   1. train a tiny digits model -> checkpoint stream
#   2. serve it: 3 replicas (one NaN-poisoned), median vote, asyncio front
#      end + continuous batching, --follow weight pipeline, --autoscale
#   3. burst leg: concurrent clients against a tiny queue bound -> 429s
#   4. calm leg: fault-masked predictions == clean baseline, /status,
#      compile_count == nb_buckets (zero steady-state recompiles)
#   5. swap leg: extend training in the same directory -> the watcher
#      hot-swaps the newer step in with zero recompiles, live
#   6. autoscale leg: sustained calm shrinks the lane pool to the floor
#   7. load leg: benchmarks/serve_load.py closed loop (sustained
#      concurrency, >=2 mid-run swaps, poisoned replica masked, SLO PASS
#      against the checked-in baseline).  Second arg "capture" re-seeds
#      benchmarks/slo_serve_cpu.json instead of judging.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_serve_smoke}"
slo_mode="${2:-check}"   # check | capture
rm -rf "$out"
mkdir -p "$out"

# ---- 1. train -> checkpoint (the model the server will load)
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:16 \
  --aggregator average --nb-workers 4 --nb-devices 1 \
  --max-step 40 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --checkpoint-dir "$out/ckpt" --checkpoint-delta 20 --checkpoint-period -1 \
  --summary-delta -1 --summary-period -1

# ---- 2. serve it: v2 stack. 3 replicas, replica 2 NaN-poisoned, median
# vote, 2 lanes, weight pipeline following the checkpoint dir, autoscaler
# with a fast calm path (the autoscale leg watches the shrink).  Tiny
# queue bound + a 150 ms linger window make the burst phase shed
# deterministically: sub-top batches hold their lane for the window, so
# the 24-deep burst piles onto the 4-row bound instead of draining as
# fast as the clients can post (the calm phase's 8-row requests fill the
# ladder top and never linger).
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.serve \
  --experiment digits --experiment-args batch-size:16 \
  --ckpt-dir "$out/ckpt" --replicas 3 --gar median --poison-replica 2:nan \
  --port 0 --ready-file "$out/ready" --summary-dir "$out/sum" \
  --max-batch 8 --queue-bound 4 --lanes 2 --max-lanes 2 --linger-ms 150 \
  --follow --follow-interval 0.5 \
  --autoscale --autoscale-args interval:0.25 down-patience:4 cooldown:0.5 &
server_pid=$!
trap 'kill "$server_pid" 2>/dev/null || true' EXIT

for _ in $(seq 1 60); do [ -f "$out/ready" ] && break; sleep 1; done
[ -f "$out/ready" ] || { echo "server never became ready"; exit 1; }

# ---- 3+4. burst (sheds) then calm (fault-masked answers + v2 status)
JAX_PLATFORMS=cpu python - "$out" <<'EOF'
import json, sys, threading, urllib.error, urllib.request

import numpy as np

out = sys.argv[1]
host, port, _pid = open("%s/ready" % out).read().split()
base = "http://%s:%s" % (host, port)

def post(payload):
    req = urllib.request.Request(base + "/predict", data=json.dumps(payload).encode(),
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())

def get(path):
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return json.loads(r.read())

# the clean baseline the poisoned server must match (median masks the NaN)
import jax
jax.config.update("jax_platforms", "cpu")
from aggregathor_tpu import models
from aggregathor_tpu.core import build_optimizer, build_schedule
from aggregathor_tpu.serve import InferenceEngine, restore_params

experiment = models.instantiate("digits", ["batch-size:16"])
tx = build_optimizer("sgd", build_schedule("fixed", ["initial-rate:0.01"]))
params, step = restore_params(experiment, "%s/ckpt" % out, tx)
x = np.asarray(experiment.dataset.x_test[:8], np.float32)
clean = InferenceEngine(experiment, [params], max_batch=8).predict(x)["predictions"]

health = get("/healthz")
assert health["status"] == "ok", health
assert health["replicas"] == 3, health
assert health["weights_step"] == step, health

# burst: 24 concurrent single-row posts against queue bound 4 -> sheds
codes = []
lock = threading.Lock()
row = x[0].tolist()
def fire():
    code, _ = post({"inputs": [row]})
    with lock:
        codes.append(code)
threads = [threading.Thread(target=fire) for _ in range(24)]
for t in threads: t.start()
for t in threads: t.join()
assert set(codes) <= {200, 429}, sorted(set(codes))
assert 429 in codes, "burst produced no shed (codes: %r)" % sorted(set(codes))

# calm phase: sequential requests all succeed with FAULT-MASKED predictions
code, served = post({"inputs": x.tolist()})
assert code == 200, (code, served)
assert served["predictions"] == [int(p) for p in clean], (
    "served predictions diverge from the clean baseline: %r vs %r"
    % (served["predictions"], list(clean)))
assert served["disagreement"][2] is None, served  # NaN replica -> null (inf)
assert served["weights_step"] == step, served
assert served["active_replicas"] == [0, 1, 2], served

status = get("/status")
assert status["weights_step"] == step, status
assert status["compile_count"] == 4, status  # ladder 1,2,4,8 compiled once

metrics = get("/metrics?format=json")
assert metrics["shed_count"] > 0, metrics
p95 = metrics["latency_ms"]["p95"]
assert p95 is not None and np.isfinite(p95), metrics
assert metrics["suspect_replicas"] == [2], metrics
assert metrics["compile_count"] == metrics["nb_buckets"], metrics  # zero steady-state recompiles
print("serve smoke OK: step-%s checkpoint, %d sheds under burst, p95=%.1f ms, "
      "poisoned replica masked + flagged" % (step, metrics["shed_count"], p95))
EOF

# ---- 5. swap leg: extend the training run -> the watcher swaps live
JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:16 \
  --aggregator average --nb-workers 4 --nb-devices 1 \
  --max-step 60 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --checkpoint-dir "$out/ckpt" --checkpoint-delta 20 --checkpoint-period -1 \
  --summary-delta -1 --summary-period -1 > /dev/null

JAX_PLATFORMS=cpu python - "$out" <<'EOF'
import json, sys, time, urllib.request

out = sys.argv[1]
host, port, _pid = open("%s/ready" % out).read().split()
base = "http://%s:%s" % (host, port)

def get(path):
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return json.loads(r.read())

# the watcher polls every 0.5 s: the newer step must swap in live
deadline = time.monotonic() + 20.0
status = get("/status")
while status["weights_step"] != 60 and time.monotonic() < deadline:
    time.sleep(0.25)
    status = get("/status")
assert status["weights_step"] == 60, (
    "watcher never hot-swapped step 60 (still %r)" % status["weights_step"])
assert status["compile_count"] == 4, status  # the swap recompiled NOTHING

# ---- 6. autoscale leg: sustained calm shrinks lanes to the floor
deadline = time.monotonic() + 20.0
while status["lanes"] != 1 and time.monotonic() < deadline:
    time.sleep(0.25)
    status = get("/status")
assert status["lanes"] == 1, "calm never shrank the lane pool: %r" % status

# a post-swap, post-shrink request still serves (and reports the new step)
row = [0.0] * 64
req = urllib.request.Request(
    base + "/predict", data=json.dumps({"inputs": [row]}).encode(),
    headers={"Content-Type": "application/json"})
with urllib.request.urlopen(req, timeout=30) as r:
    served = json.loads(r.read())
assert served["weights_step"] == 60, served
print("swap + autoscale legs OK: weights_step 60 live (0 recompiles), "
      "lanes shrunk to 1 under calm")
EOF

# ---- graceful shutdown (SIGTERM must not wedge the event loop)
kill "$server_pid"
for _ in $(seq 1 20); do kill -0 "$server_pid" 2>/dev/null || break; sleep 0.5; done
if kill -0 "$server_pid" 2>/dev/null; then
  echo "server ignored SIGTERM"; kill -9 "$server_pid"; exit 1
fi
trap - EXIT

# the summary stream carries the serve events (incl. swap + autoscale)
python - "$out/sum" <<'EOF'
import json, os, sys
sum_dir = sys.argv[1]
events = [json.loads(line)
          for name in os.listdir(sum_dir)
          for line in open(os.path.join(sum_dir, name))]
batches = [e for e in events if e.get("event") == "serve_batch"]
sheds = [e for e in events if e.get("event") == "serve_shed"]
swaps = [e for e in events if e.get("event") == "serve_weight_swap"]
scales = [e for e in events if e.get("event") == "serve_autoscale"]
assert batches, "no serve_batch summary events"
assert sheds, "no serve_shed summary events"
assert swaps, "no serve_weight_swap summary events"
assert scales, "no serve_autoscale summary events"
print("summary stream OK: %d serve_batch + %d serve_shed + %d "
      "serve_weight_swap + %d serve_autoscale event(s)"
      % (len(batches), len(sheds), len(swaps), len(scales)))
EOF

# ---- 7. load leg: the closed loop, judged against the checked-in SLO
if [ "$slo_mode" = "capture" ]; then
  JAX_PLATFORMS=cpu python benchmarks/serve_load.py --duration 5 \
    --out "$out/load.json" --slo-capture benchmarks/slo_serve_cpu.json
  echo "serve SLO baseline re-seeded (benchmarks/slo_serve_cpu.json)"
else
  JAX_PLATFORMS=cpu python benchmarks/serve_load.py --duration 5 \
    --out "$out/load.json" --slo benchmarks/slo_serve_cpu.json
fi

echo "serve smoke PASSED"

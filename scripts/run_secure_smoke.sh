#!/usr/bin/env bash
# Secure-layer smoke on CPU (<60 s), docs/security.md: one --secure training
# run with an injected forger through the REAL CLI, then assert
#   1. the forensics report NAMES the forger (worker 0) with 'forgery'
#      evidence and the final loss is finite (the run converged THROUGH the
#      rejected submissions),
#   2. secure_verify_seconds_total is nonzero in the Prometheus dump (the
#      security tax is measured, not presumed),
#   3. custody manifests land beside every snapshot and serving REFUSES an
#      unsigned checkpoint but starts custody-verified with the secret
#      (/healthz custody_verified == true) — train -> sign -> serve,
#   4. the secure-overhead benchmark document round-trips its schema.
set -euo pipefail
cd "$(dirname "$0")/.."

out="${1:-/tmp/aggregathor_secure}"
secret="smoke-session-secret"
rm -rf "$out"
mkdir -p "$out/sum"

JAX_PLATFORMS=cpu python -m aggregathor_tpu.cli.runner \
  --experiment digits --experiment-args batch-size:16 \
  --aggregator median --nb-workers 6 --nb-decl-byz-workers 1 \
  --nb-real-byz-workers 1 --chaos "0:calm 6:forge=1.0" \
  --max-step 18 --learning-rate-args initial-rate:0.05 --prefetch 0 \
  --evaluation-delta -1 --evaluation-period -1 \
  --summary-dir "$out/sum" --summary-delta 6 \
  --secure --session-secret "$secret" \
  --checkpoint-dir "$out/ckpt" --checkpoint-delta 9 \
  --metrics-file "$out/train.prom" \
  --forensics "$out/forensics.json" --run-id secsmoke01

python - "$out" <<'EOF'
import json, os, sys

out = sys.argv[1]

# ---- 1: forensics names the forger, run converged --------------------- #
report = json.load(open(os.path.join(out, "forensics.json")))
assert report["schema"] == "aggregathor.obs.forensics.v1"
assert report["suspects"] == [0], (
    "forensics named %r, expected the forging worker [0]" % report["suspects"])
evidence = report["workers"][0]["evidence"]
assert evidence.get("forgery", 0) > 0, evidence
lines = [json.loads(line)
         for name in os.listdir(os.path.join(out, "sum"))
         for line in open(os.path.join(out, "sum", name))]
losses = [l["total_loss"] for l in lines if "total_loss" in l]
assert losses and all(abs(v) < float("inf") for v in losses), losses
print("forensics OK: forger named with %d forgery entries, final loss %.4f"
      % (evidence["forgery"], losses[-1]))

# ---- 2: the security tax is measured ---------------------------------- #
from aggregathor_tpu.obs.metrics import parse_prometheus

parsed = parse_prometheus(open(os.path.join(out, "train.prom")).read())
verify = dict((n, v) for n, l, v in parsed["secure_verify_seconds_total"]["samples"])
sign = dict((n, v) for n, l, v in parsed["secure_sign_seconds_total"]["samples"])
assert verify["secure_verify_seconds_total"] > 0.0
assert sign["secure_sign_seconds_total"] > 0.0
forgeries = {l["worker"]: v for n, l, v in parsed["secure_forgeries_total"]["samples"]}
assert set(forgeries) == {"0"} and forgeries["0"] > 0, forgeries
print("metrics OK: sign %.3f ms, verify %.3f ms total, %d forgeries (worker 0 only)"
      % (sign["secure_sign_seconds_total"] * 1e3,
         verify["secure_verify_seconds_total"] * 1e3, int(forgeries["0"])))

# ---- 3a: custody manifests beside every snapshot ---------------------- #
ckpt = os.path.join(out, "ckpt")
snapshots = sorted(n for n in os.listdir(ckpt) if n.endswith(".ckpt"))
manifests = sorted(n for n in os.listdir(ckpt) if n.endswith(".manifest.json"))
assert snapshots and len(manifests) == len(snapshots), (snapshots, manifests)
doc = json.load(open(os.path.join(ckpt, manifests[-1])))
assert doc["schema"] == "aggregathor.secure.custody.v1"
assert doc["run_id"] == "secsmoke01" and doc["gar"].startswith("f=1")
assert doc["tag_chain"]["steps"] > 0 and doc["tag_chain"]["nb_workers"] == 6
print("custody OK: %d manifest(s), tag chain over %d step(s)"
      % (len(manifests), doc["tag_chain"]["steps"]))
EOF

# ---- 3b: custody-verified serve startup; unsigned refused ------------- #
JAX_PLATFORMS=cpu python - "$out" "$secret" <<'EOF'
import glob, json, os, shutil, sys, urllib.request

out, secret = sys.argv[1], sys.argv[2]
sys.argv = [sys.argv[0]]

from aggregathor_tpu import models
from aggregathor_tpu.cli import serve as serve_cli
from aggregathor_tpu.serve import InferenceEngine, InferenceServer
from aggregathor_tpu.utils import UserException

experiment = models.instantiate("digits", ["batch-size:16"])
argv = ["--experiment", "digits", "--experiment-args", "batch-size:16",
        "--ckpt-dir", os.path.join(out, "ckpt"), "--replicas", "2",
        "--gar", "median", "--session-secret", secret, "--max-batch", "4"]
args = serve_cli.build_parser().parse_args(argv)
replicas, sources, verified = serve_cli.load_replicas(args, experiment)
assert verified is True, "custody must verify at serve startup"
engine = InferenceEngine(experiment, replicas, max_batch=4)
engine.warmup()
server = InferenceServer(engine, port=0, custody_verified=verified)
host, port = server.serve_background()
try:
    health = json.loads(urllib.request.urlopen(
        "http://%s:%d/healthz" % (host, port), timeout=10).read())
    assert health["custody_verified"] is True, health
finally:
    server.shutdown_all()
print("serve OK: custody_verified true in /healthz")

# an UNSIGNED checkpoint directory is refused without --allow-unsigned
plain = os.path.join(out, "ckpt_unsigned")
shutil.copytree(os.path.join(out, "ckpt"), plain)
for manifest in glob.glob(os.path.join(plain, "*.manifest.json")):
    os.remove(manifest)
args = serve_cli.build_parser().parse_args(
    argv[:5] + [plain] + argv[6:])
try:
    serve_cli.load_replicas(args, experiment)
    raise SystemExit("unsigned checkpoint must be refused")
except UserException as exc:
    assert "custody manifest" in str(exc)
args = serve_cli.build_parser().parse_args(
    argv[:5] + [plain] + argv[6:] + ["--allow-unsigned"])
_, _, verified = serve_cli.load_replicas(args, experiment)
assert verified is False
print("serve OK: unsigned refused; --allow-unsigned loads with custody_verified false")
EOF

# ---- 4: benchmark schema round-trip (small geometry, schema contract) -- #
JAX_PLATFORMS=cpu python benchmarks/secure_overhead.py \
  --n 8 --d 1024 --steps 6 --repeats 1 --bar 1000 \
  --output "$out/secure_overhead.json" >/dev/null
python - "$out" <<'EOF'
import json, os, sys
sys.path.insert(0, "benchmarks")
from secure_overhead import validate_secure_overhead

doc = validate_secure_overhead(json.load(open(os.path.join(sys.argv[1], "secure_overhead.json"))))
print("benchmark OK: schema %s, tax %+.2f%%, sign %.3f ms/step"
      % (doc["schema"], doc["overhead_pct"],
         doc["host_crypto"]["sign_ms_per_step"]))
EOF

echo "secure smoke OK: $out"

"""Shared GAR numerics: distances, NaN conventions, rank selections.

NaN conventions follow the reference: a non-finite pairwise distance counts as
+inf for scoring (reference: aggregators/krum.py:71-73), and non-finite
coordinates sort *last* (as if +inf) in the coordinate-wise rules (reference:
aggregators/deprecated_native/native.cpp:691-697).  XLA is instructed not to
strip this handling by using explicit ``isfinite`` masking rather than NaN
comparisons.
"""

import os

import jax
import jax.numpy as jnp


def nonfinite_to_inf(x):
    """Replace every non-finite entry with +inf (NaN-last ordering convention)."""
    return jnp.where(jnp.isfinite(x), x, jnp.inf)


#: Column count above which the Pallas coordinate kernels serve a TPU block.
#: Measured on the v5e (round 4, benchmarks/tpu_capture.jsonl pallas_check):
#: at d=65k the Pallas rank-select already wins (averaged-median 1.4 ms vs
#: 16.9 ms for the XLA sort path) and the gap widens with d (8.4M: median
#: 8.2 ms vs 168 ms, averaged-median 16 ms vs 3871 ms); below ~16k columns a
#: per-call pad+launch is not worth displacing one small fused sort.
PALLAS_MIN_COLUMNS = 16384

def _is_batched_tracer(x):
    """True when ``x`` is being traced under ``jax.vmap`` (batching trace).

    Both engines call the rules under vmap on their bucketed paths
    (engine._aggregate_per_leaf_bucketed, sharded_engine's per-bucket
    loop); a vmapped ``pallas_call`` lowers through Pallas' batching rule,
    which the CPU suite exercises only in interpret mode and which is
    UNVALIDATED on real TPU silicon here (scripts/pallas_tpu_check.py's
    ``*-vmap4`` rows are the armed proof).  Detecting the batching trace
    centrally means no call site can forget an opt-out wrapper; the
    explicit ``GRAFT_GAR_TIER=pallas`` force remains the one way to
    exercise the vmapped Pallas path end to end.

    Detection is isinstance-first against the real tracer class (imported
    from its current `_src` home), with the class-NAME scan as fallback in
    case the module moves in a future JAX — a false negative here would
    silently re-enable the unproven path, so
    ``tests/test_pallas.py::test_batched_tracer_detected_under_vmap``
    fails loudly if neither detection fires under ``jax.vmap``.
    """
    if _BATCH_TRACER_CLS is not None and isinstance(x, _BATCH_TRACER_CLS):
        return True
    return any(c.__name__ == "BatchTracer" for c in type(x).__mro__)


try:  # the canonical home today; the name-scan above covers a future move
    from jax._src.interpreters.batching import BatchTracer as _BATCH_TRACER_CLS
except ImportError:  # pragma: no cover
    _BATCH_TRACER_CLS = None


def use_pallas_coordinate_tier(block):
    """Backend auto-dispatch for the coordinate-wise selection rules.

    Mirrors the reference's tier policy — the C++ custom op serves the rule
    when loadable, the graph tier otherwise (aggregators/median.py:40-48) —
    re-targeted at XLA: on TPU, large column blocks go to the hand-written
    Pallas rank-selection kernels (ops/pallas_kernels.py), which make the
    SAME selections as the jnp tier (same ranks, same tie-breaks) and agree
    numerically to float tolerance — the summation order of averaged means
    differs, so low bits can (asserted on NaN-poisoned inputs by
    tests/test_pallas.py and on silicon by scripts/pallas_tpu_check.py).
    ``GRAFT_GAR_TIER=jnp|pallas`` forces a tier (tests, A/B timing).

    Gating note (ADVICE r4): unlike the vmapped path (suspended until its
    armed silicon proof lands), the un-batched in-engine tier stays ON by
    default even though its standalone-kernel silicon proof does not cover
    the full shard_map/scan step — the kernels make the same selections as
    the jnp tier by construction, the train_configs 2d/3d stages are armed
    to exercise exactly this path on silicon, and ``GRAFT_GAR_TIER=jnp``
    is the escape hatch if they surface a divergence.
    """
    forced = os.environ.get("GRAFT_GAR_TIER")
    if forced == "pallas":
        return True  # explicit force outranks the vmap suspension: it is
        # the only way to exercise/A-B the vmapped Pallas path end to end
    if _is_batched_tracer(block):
        return False  # vmapped call: see _is_batched_tracer
    if forced == "jnp":
        return False
    return (
        jax.default_backend() == "tpu"
        and block.ndim == 2
        and block.shape[1] >= PALLAS_MIN_COLUMNS
    )


#: n²·d element budget above which ``centered_gram_sq_distances`` chunks its
#: Gram matmul over the coordinate axis: at large n (the hier/bucketing
#: regime, n=128..512) one monolithic (n, d)x(d, n) contraction forces the
#: scheduler to stage the whole centered operand through fast memory at
#: once, while d-chunked accumulation bounds the working set without
#: changing the O(n²·d) arithmetic.
GRAM_CHUNK_BUDGET = 1 << 31


def centered_gram_sq_distances(g, chunk_budget=GRAM_CHUNK_BUDGET):
    """Gram-form all-pairs squared distances of (n, d) rows, median-centered.

    The Gram form ``|a|² + |b|² - 2·a·b`` is one MXU matmul but suffers
    catastrophic cancellation when rows share a large common mode, so rows
    are first centered by their coordinate-wise (NaN-ignoring) median —
    distances are translation-invariant and the robust center keeps the
    conditioning independent of Byzantine outliers.  Shared by the dense tier
    below and the sharded engine's per-block partial distances.

    When ``n²·d`` exceeds ``chunk_budget`` the (n, n) Gram is accumulated
    over coordinate chunks with one ``lax.scan`` (zero-padded tail — the
    padding is applied AFTER centering, so it contributes nothing to norms
    or inner products); within a chunked run the float accumulation order
    differs from the monolithic matmul by ordinary non-associativity, same
    as any blocking choice XLA could make itself.
    """
    n, d = g.shape
    center = jnp.nan_to_num(jnp.nanmedian(jnp.where(jnp.isfinite(g), g, jnp.nan), axis=0))
    g = g - center[None, :]
    sq_norms = jnp.sum(g * g, axis=-1)
    if n * n * d <= chunk_budget:
        gram = jax.lax.dot_general(
            g, g, (((1,), (1,)), ((), ())), precision=jax.lax.Precision.HIGHEST
        )
    else:
        chunk = max(128, min(d, chunk_budget // max(n * n, 1)))
        pad = (-d) % chunk
        gp = jnp.pad(g, ((0, 0), (0, pad))) if pad else g
        chunks = gp.reshape(n, (d + pad) // chunk, chunk).transpose(1, 0, 2)

        def body(acc, block):
            partial = jax.lax.dot_general(
                block, block, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
            )
            return acc + partial, None

        gram, _ = jax.lax.scan(body, jnp.zeros((n, n), jnp.float32), chunks)
    return sq_norms[:, None] + sq_norms[None, :] - 2.0 * gram


def pairwise_sq_distances(grads, direct_threshold=1 << 22):
    """All-pairs squared L2 distances of the rows of an (n, d) matrix.

    Two regimes:
    - small n²·d (tests, tiny models): the direct broadcasted ``sum((a-b)²)``,
      bitwise-faithful to the reference's CPU loop (op_krum/cpu.cpp:53-122);
    - large d: the Gram form ``|a|² + |b|² - 2·a·b`` so the O(n²·d) work is a
      single (n, d)x(d, n) matmul on the MXU.  The Gram form suffers
      catastrophic cancellation when vectors share a large common mode, so
      rows are first centered by their coordinate-wise (NaN-ignoring) median —
      distances are translation-invariant and the robust center keeps the
      conditioning independent of Byzantine outliers.

    NaN rows propagate to NaN distances, which downstream scoring maps to
    +inf, matching the reference's convention.  Accumulates in float32.
    """
    g = grads.astype(jnp.float32)
    n, d = g.shape
    if n * n * d <= direct_threshold:
        diff = g[:, None, :] - g[None, :, :]
        return jnp.sum(diff * diff, axis=-1)
    if use_pallas_coordinate_tier(g):
        from ..ops import pallas_kernels as pk

        return pk.pairwise_sq_distances(g)
    dist2 = centered_gram_sq_distances(g)
    return jnp.maximum(dist2, 0.0)  # clamp matmul-form negatives; NaN passes through


def smallest_k_sum(values, k, axis=-1):
    """Sum of the k smallest entries along ``axis`` (non-finite counts as +inf)."""
    if axis != -1:
        raise ValueError("smallest_k_sum supports axis=-1 only")
    clean = nonfinite_to_inf(values)
    return jnp.sum(jnp.sort(clean, axis=axis)[..., :k], axis=axis)


def smallest_k_mask(scores, k):
    """Boolean (n,) mask of the k smallest scores (ties broken by lowest index).

    Non-finite scores count as +inf.  Implemented with a rank comparison so it
    lowers to pure vector ops (no gather/scatter) — cheap on TPU.
    """
    clean = nonfinite_to_inf(scores)
    n = clean.shape[0]
    idx = jnp.arange(n)
    # rank(i) = number of entries strictly smaller, plus earlier-index ties
    smaller = (clean[None, :] < clean[:, None]) | ((clean[None, :] == clean[:, None]) & (idx[None, :] < idx[:, None]))
    ranks = jnp.sum(smaller, axis=1)
    return ranks < k


def selection_mean_weights(scores, k):
    """(n,) weights averaging the k smallest-scoring rows: mask / k.

    ``k`` may be a Python int or a traced scalar (Bulyan's lax.scan passes
    the round index)."""
    return smallest_k_mask(scores, k).astype(jnp.float32) / jnp.asarray(k, jnp.float32)


def alive_rows(rows, axis_name=None):
    """Global row liveness for NaN-absorbing iterative rules.

    Returns ``(alive, safe)``: the (n,) float mask of rows with NO
    non-finite coordinate (counted across dimension blocks by psum when
    ``axis_name`` is given, so every shard agrees) and the rows with dead
    entries zero-filled.  The average-nan convention: dead rows weigh 0."""
    nb_bad = jnp.sum(~jnp.isfinite(rows), axis=-1).astype(jnp.float32)
    if axis_name is not None:
        nb_bad = jax.lax.psum(nb_bad, axis_name)
    alive = (nb_bad == 0.0).astype(jnp.float32)
    return alive, jnp.where((alive > 0.0)[:, None], rows, 0.0)


def masked_coordinate_median(rows, alive):
    """Coordinate-wise median of the alive rows (0 where all rows are dead).
    Per-coordinate: needs no cross-block information."""
    return jnp.nan_to_num(
        jnp.nanmedian(jnp.where((alive > 0.0)[:, None], rows, jnp.nan), axis=0)
    )


def global_row_sq_norms(deviation, axis_name=None):
    """(n,) squared row norms, completed across dimension blocks by psum."""
    sqn = jnp.sum(deviation * deviation, axis=-1)
    if axis_name is not None:
        sqn = jax.lax.psum(sqn, axis_name)
    return sqn


def memo_by_identity(method):
    """Memoize a one-argument method on argument IDENTITY.

    ``aggregate_block`` and ``worker_participation`` both derive from
    ``selection_weights(dist2)`` within the same traced step; without this,
    the selection graph (O(n² log n) rank sort + the Bulyan t-round loop) is
    traced twice and dedup relies on XLA CSE.  Identity keying is
    trace-safe: a retrace passes a fresh tracer, misses, and overwrites the
    stale entry (which is never used again).

    The entry holds a (tracer-arg, tracer-result) tuple, so the OUTER call
    must drop it once the pass is done (``_GAR._drop_memos``, called from
    ``aggregate``/``aggregate_block_and_participation``) — a stale entry
    keeps the traced selection graph alive for the instance's lifetime and
    trips ``jax.check_tracer_leaks``."""
    import functools

    attr = "_memo_" + method.__name__

    @functools.wraps(method)
    def wrapped(self, arg):
        cached = getattr(self, attr, None)
        if cached is not None and cached[0] is arg:
            return cached[1]
        out = method(self, arg)
        setattr(self, attr, (arg, out))
        return out

    return wrapped


def select_combine(weights, block):
    """Weighted row combination that ignores NaNs in *unselected* rows.

    ``weights @ block`` alone would propagate NaN from rows with weight 0
    (0 x NaN = NaN), which would let an excluded Byzantine/NaN worker poison
    the output.  The reference's gather-then-mean never touches unselected
    rows (krum.py:93); to reproduce that with matmuls: sanitize non-finite
    entries to 0 for the combine, then re-poison exactly the coordinates
    where a row with *nonzero* weight was non-finite.

    Args:
      weights: (n,) or (t, n) selection weights.
      block:   (n, d_block) gradient rows.
    Returns:
      (d_block,) or (t, d_block) combined rows, NaN-faithful.
    """
    w = weights if weights.ndim == 2 else weights[None, :]
    finite = jnp.isfinite(block)
    safe = jnp.where(finite, block, 0.0)
    out = w.astype(jnp.float32) @ safe.astype(jnp.float32)
    touched = (jnp.abs(w) > 0).astype(jnp.float32) @ (~finite).astype(jnp.float32)
    out = jnp.where(touched > 0, jnp.nan, out)
    return out if weights.ndim == 2 else out[0]

"""Plain averaging GAR (not Byzantine-tolerant; the f=0 baseline).

Reference: aggregators/average.py:40-60 (``tf.add_n(gradients)/n``).
Coordinate-wise, so in distributed mode this lowers to a plain mean over the
worker axis — exactly a psum/allreduce, the non-robust fast path.
"""

import jax.numpy as jnp

from . import GAR, register


class AverageGAR(GAR):
    coordinate_wise = True

    def aggregate_block(self, block, dist2=None):
        return jnp.mean(block, axis=0)


register("average", AverageGAR)

"""Pallas-tier GAR registrations (``*-pallas``).

Counterpart of the reference's ``-co`` custom-op tier (aggregators/krum.py:
142-158, bulyan.py:68-84), re-targeted at the TPU: the coordinate-wise
selection and the pairwise-distance streaming run as hand-written Pallas
kernels (ops/pallas_kernels.py) instead of C++/CUDA.  Off-TPU the kernels
execute in interpreter mode, so the tier is usable (slowly) everywhere and
the CPU test suite exercises the exact kernel code path.

The distance-based rules use the Pallas distance kernel on the dense path;
their O(n²) scoring stays jnp (it is tiny and replicated).  Blockwise, the
coordinate kernels apply per column block unchanged.
"""

from . import register
from .average_nan import AverageNaNGAR
from .averaged_median import AveragedMedianGAR
from .bulyan import BulyanGAR
from .krum import KrumGAR
from .median import MedianGAR
from .trimmed_mean import TrimmedMeanGAR
from .common import select_combine
from ..ops import pallas_kernels as pk


class PallasMedianGAR(MedianGAR):
    def aggregate_block(self, block, dist2=None):
        return pk.coordinate_median(block)


class PallasAveragedMedianGAR(AveragedMedianGAR):
    def aggregate_block(self, block, dist2=None):
        return pk.coordinate_averaged_median(block, self.beta)


class PallasAverageNaNGAR(AverageNaNGAR):
    def aggregate_block(self, block, dist2=None):
        return pk.average_nan_columns(block)


class PallasKrumGAR(KrumGAR):
    def aggregate(self, grads, key=None):
        try:
            dist2 = pk.pairwise_sq_distances(grads)
            return self.aggregate_block(grads, dist2)
        finally:
            self._drop_memos()


class PallasBulyanGAR(BulyanGAR):
    def aggregate(self, grads, key=None):
        try:
            dist2 = pk.pairwise_sq_distances(grads)
            return self.aggregate_block(grads, dist2)
        finally:
            self._drop_memos()

    def aggregate_block(self, block, dist2=None):
        assert dist2 is not None, "bulyan requires the pairwise distance matrix"
        selections = select_combine(self.selection_weights(dist2), block)
        return pk.coordinate_averaged_median(selections, self.nb_closest)


class PallasTrimmedMeanGAR(TrimmedMeanGAR):
    def aggregate_block(self, block, dist2=None):
        return pk.coordinate_trimmed_mean(
            block, self.nb_trim, self.nb_workers - 2 * self.nb_trim
        )


register("median-pallas", PallasMedianGAR)
register("trimmed-mean-pallas", PallasTrimmedMeanGAR)
register("averaged-median-pallas", PallasAveragedMedianGAR)
register("average-nan-pallas", PallasAverageNaNGAR)
register("krum-pallas", PallasKrumGAR)
register("bulyan-pallas", PallasBulyanGAR)

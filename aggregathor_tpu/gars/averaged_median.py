"""Averaged-median GAR: per coordinate, average the beta = n - f values
closest to the (upper) median.

Reference: aggregators/averaged-median.py:40-67 (beta = nbworkers - nbbyzwrks)
backed by deprecated_native/native.cpp:714-747 (nth_element to the median,
then nth_element by |x - median| and average of the first beta).

Non-finite coordinates get +inf deviation so they are only selected when beta
forces it (the reference's comparator leaves NaN ordering unspecified; the
explicit mask makes this tier deterministic).
"""

import jax.numpy as jnp

from . import GAR, register
from .common import nonfinite_to_inf, use_pallas_coordinate_tier


def averaged_median_columns(block, nb_rows, beta):
    """Per-column averaged-median over the first axis: median, then mean of
    the ``beta`` entries closest to it.  Shared with Bulyan's final phase.

    On TPU, large blocks dispatch to the fused Pallas kernel (identical
    selection; the largest measured tier gap — 16 ms vs 3871 ms at d=8.4M,
    see ``use_pallas_coordinate_tier``)."""
    from .median import median_columns

    if block.shape[0] == nb_rows and use_pallas_coordinate_tier(block):
        from ..ops import pallas_kernels as pk

        return pk.coordinate_averaged_median(block, beta)
    median = median_columns(block, nb_rows)
    deviation = nonfinite_to_inf(jnp.abs(block - median[None, :]))
    order = jnp.argsort(deviation, axis=0)[:beta]
    closest = jnp.take_along_axis(block, order, axis=0)
    return jnp.mean(closest, axis=0)


class AveragedMedianGAR(GAR):
    coordinate_wise = True
    # NOT nan_row_tolerant: with more dead rows than the beta = n - f budget
    # covers, inf-deviation rows are force-selected and the mean goes NaN

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        self.beta = self.nb_workers - self.nb_byz_workers
        if self.beta < 1:
            from ..utils import UserException

            raise UserException("averaged-median needs n - f >= 1 (got n=%d, f=%d)" % (nb_workers, nb_byz_workers))

    def aggregate_block(self, block, dist2=None):
        return averaged_median_columns(block, self.nb_workers, self.beta)


register("averaged-median", AveragedMedianGAR)

"""Coordinate-wise trimmed mean GAR (Yin et al. 2018, "Byzantine-Robust
Distributed Learning: Towards Optimal Statistical Rates").

An extension beyond the reference's rule set (aggregators/ has no trimmed
mean): per coordinate, drop the ``b`` largest and ``b`` smallest values and
average the middle ``n - 2b``.  With ``b = f`` (the default) the estimator
achieves order-optimal statistical rates under up to ``f`` Byzantine
workers.  Non-finite values sort to the *ends* (they are what trimming
exists to remove): each non-finite entry is mapped to +/-inf by sign-of-NaN
irrelevance — we place all of them at the top end, so a column with more
than ``b`` non-finite entries is visibly poisoned (NaN output) rather than
silently wrong, matching the NaN-faithfulness convention of the other
coordinate-wise rules (gars/common.py).
"""

import jax.numpy as jnp

from . import GAR, register
from .common import nonfinite_to_inf, use_pallas_coordinate_tier


def trimmed_mean_columns(block, nb_rows, nb_trim):
    """Per-column mean of the middle ``nb_rows - 2*nb_trim`` sorted values.

    On TPU, large blocks dispatch to the Pallas rank-selection kernel
    (same selected multiset per column; see
    ``common.use_pallas_coordinate_tier``)."""
    keep = nb_rows - 2 * nb_trim
    if block.shape[0] == nb_rows and use_pallas_coordinate_tier(block):
        from ..ops import pallas_kernels as pk

        return pk.coordinate_trimmed_mean(block, nb_trim, keep)
    clean = nonfinite_to_inf(block)
    ordered = jnp.sort(clean, axis=0)[nb_trim:nb_trim + keep]
    # Columns whose kept band still contains inf had > nb_trim poisoned
    # entries: surface NaN (GAR bound void), never a silently-huge mean.
    out = jnp.mean(ordered, axis=0)
    return jnp.where(jnp.isfinite(out), out, jnp.nan)


class TrimmedMeanGAR(GAR):
    coordinate_wise = True
    ARG_DEFAULTS = {"trim": -1}  # -1: trim f from each end

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        trim = int(self.args["trim"])
        self.nb_trim = self.nb_byz_workers if trim < 0 else trim
        if self.nb_workers - 2 * self.nb_trim < 1:
            from ..utils import UserException

            raise UserException(
                "trimmed-mean needs n - 2*trim >= 1 (got n=%d, trim=%d)"
                % (self.nb_workers, self.nb_trim)
            )

    def aggregate_block(self, block, dist2=None):
        return trimmed_mean_columns(block, self.nb_workers, self.nb_trim)


register("trimmed-mean", TrimmedMeanGAR)

"""Hierarchical (tree-reduction) meta-GAR — the large-n fast path.

Motivated by efficient meta-aggregation (arXiv:2405.14759) and
tree-structured reduction (CodedReduce, arXiv:1902.01981): the flagship
rules (Krum, Bulyan) are O(n²·d) on the stacked (n, d) matrix, which is the
cost wall that keeps n small.  ``hier`` composes two registered rules into a
two-level tree::

    hier:g=16,inner=median,outer=krum

    groups   = reshape the n workers into n/g contiguous groups of g
    summary  = inner(group)   per group   — one cheap O(g·d) pass, vmapped
    output   = outer(summaries)           — the expensive rule over n/g rows

so the n²·d term shrinks to (n/g)²·d plus an O(n·d) group pass.  With g
grown ~n/const the outer matrix stays constant-sized and total work is
linear in n — sublinear in n² (benchmarks/gar_kernels.py ``--sweep-ns``
measures exactly this claim).

**Byzantine bookkeeping.**  Groups are a *partition*: f Byzantine workers
can corrupt at most f group summaries (each worker sits in exactly one
group), so the outer rule runs over ``n/g`` rows with the SAME declared
``f`` — its (n/g, f) feasibility is validated here at parse time, exactly
like :class:`~aggregathor_tpu.gars.bucketing.BucketingGAR` validates its
inner rule.  The inner rule is best-effort damage control *within* a group
(a group with a Byzantine minority may still emit an honest-cloud summary);
it is instantiated with ``inner_f = min(f, g - 1)`` by default
(``inner_f=K`` overrides) and its own feasibility check also runs at parse
time.  The f-breakdown property is carried by the OUTER level: even if
every contaminated group's summary is fully adversarial, at most f of the
n/g outer rows are Byzantine — the bound the outer rule is sized for.

**TPU mapping.**  The inner pass is the (n/g, g, d_block) reshape vmapped
over groups — pure jnp tier: the vmapped-Pallas suspension in
``gars/common.py`` (``_is_batched_tracer``) detects the batching trace
centrally, so no Pallas kernel is reached under the group vmap until its
silicon proof lands.  Inner distance matrices (when the inner rule needs
them) are per-group (g, g) centered Grams completed with one psum across
dimension blocks under ``uses_axis``; the outer distances are one
(n/g, n/g) centered Gram, same discipline as ``bucketing.py``.

**NaN rows (lossy link).**  A dead worker's NaN row is absorbed at the
first level that cleanly excludes it: a NaN-tolerant inner drops it from
the group summary; a non-tolerant inner (e.g. ``average``) lets it poison
the summary, and a NaN-tolerant outer then excludes that group row — so
``nan_row_tolerant`` holds whenever either level's rule declares it.

**Nesting.**  ``hier`` composes with ``bucketing`` in both directions
(``bucketing:inner=hier(g=8,outer=krum)`` or ``hier:outer=bucketing(...)``)
— nested specs use the parenthesized form so their commas stay attached
(gars/__init__.py ``parse_spec``).  Randomized nested rules re-draw every
step: per-group inner keys derive from fold_in(key, 1) + the group index,
the outer key from fold_in(key, 2) — all disjoint, all replicated.

**Participation.**  Worker i's weight factorizes through the tree:
``outer_participation[group(i)] * inner_participation_within_group(i)``
(uniform 1/g when the inner rule defines none).  Each group's inner
weights sum to 1 and the outer weights sum to 1, so the scattered (n,)
vector sums to 1 — the convention the suspicion diagnostics rely on.
"""

import jax
import jax.numpy as jnp

from . import GAR, instantiate, register
from .common import centered_gram_sq_distances


class HierarchicalGAR(GAR):
    coordinate_wise = False
    needs_distances = False  # distances (if any) are per level, computed here
    uses_axis = True
    uses_key = True
    #: optional ``secure.masking.GroupMasking`` (requires ``inner=average``,
    #: validated by ``secure.masking.enable_masking``): group summaries are
    #: computed in the exact masked integer domain
    masking = None
    ARG_DEFAULTS = {"g": 4, "inner": "median", "outer": "krum", "inner_f": -1}

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        from ..utils import UserException

        self.g = int(self.args["g"])
        if self.g < 1 or self.nb_workers % self.g != 0:
            raise UserException(
                "hier needs a group size g >= 1 dividing n (got n=%d, g=%r)"
                % (self.nb_workers, self.args["g"])
            )
        self.nb_groups = self.nb_workers // self.g
        # f workers corrupt at most f groups (a partition): the outer rule
        # sees n/g rows with the same declared f — its (n/g, f) feasibility
        # check runs HERE, at parse time (the composition is rejected before
        # any training step if the tree cannot honor the budget).
        self.outer = instantiate(str(self.args["outer"]), self.nb_groups, self.nb_byz_workers)
        # The inner rule is within-group best effort; a group may hold up to
        # min(f, g) Byzantine members, clamped to what any rule can admit.
        inner_f = int(self.args["inner_f"])
        if inner_f < 0:
            inner_f = min(self.nb_byz_workers, self.g - 1)
        if inner_f > self.g:
            raise UserException(
                "hier inner_f=%d exceeds the group size g=%d" % (inner_f, self.g)
            )
        self.inner_f = inner_f
        self.inner = instantiate(str(self.args["inner"]), self.g, inner_f)
        # A NaN row is excluded by whichever level first absorbs it: the
        # inner drops it from the summary, or it poisons the summary and the
        # outer drops that group row.
        self.nan_row_tolerant = self.inner.nan_row_tolerant or self.outer.nan_row_tolerant

    # ------------------------------------------------------------------ #

    def _grouped(self, block):
        return block.reshape(self.nb_groups, self.g, block.shape[-1])

    def _inner_call(self, grouped, axis_name, key, with_participation):
        """vmapped inner pass: (n/g, g, d_block) -> (n/g, d_block) summaries
        (+ per-group (n/g, g) participation when requested)."""
        if self.masking is not None:
            # Masked group means (secure/masking.py): inner=average computed
            # in the exact mod-2^64 masked domain — rows one-time-padded
            # within their group, a dropped row NaNs its group summary and
            # the NaN-tolerant outer absorbs it.  Participation within a
            # group is uniform 1/g, exactly like plain average's.
            from ..secure.masking import masked_group_mean

            summaries = masked_group_mean(
                grouped, key, self.masking, axis_name=axis_name
            )
            return summaries, None
        inner = self.inner
        dist2 = None
        if inner.needs_distances:
            partial = jax.vmap(centered_gram_sq_distances)(grouped.astype(jnp.float32))
            if axis_name is not None:
                partial = jax.lax.psum(partial, axis_name)
            dist2 = jnp.maximum(partial, 0.0)
        keys = None
        if key is not None:
            base = jax.random.fold_in(key, 1)
            keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
                jnp.arange(self.nb_groups)
            )

        def one(rows, d2, k):
            if with_participation:
                return inner.aggregate_block_and_participation(
                    rows, d2, axis_name=axis_name, key=k
                )
            return inner._call_aggregate(rows, d2, axis_name=axis_name, key=k), None

        in_axes = (0, 0 if dist2 is not None else None, 0 if keys is not None else None)
        return jax.vmap(one, in_axes=in_axes)(grouped, dist2, keys)

    def _outer_dist2(self, summaries, axis_name):
        if not self.outer.needs_distances:
            return None
        partial = centered_gram_sq_distances(summaries.astype(jnp.float32))
        if axis_name is not None:
            partial = jax.lax.psum(partial, axis_name)
        return jnp.maximum(partial, 0.0)

    def _outer_key(self, key):
        # disjoint from the per-group inner streams (fold_in(key, 1) + gidx)
        return None if key is None else jax.random.fold_in(key, 2)

    # ------------------------------------------------------------------ #

    def aggregate_block(self, block, dist2=None, axis_name=None, key=None):
        summaries, _ = self._inner_call(self._grouped(block), axis_name, key, False)
        return self.outer._call_aggregate(
            summaries, self._outer_dist2(summaries, axis_name),
            axis_name=axis_name, key=self._outer_key(key),
        )

    def aggregate_block_and_participation(self, block, dist2=None, axis_name=None, key=None):
        summaries, inner_part = self._inner_call(self._grouped(block), axis_name, key, True)
        agg, outer_part = self.outer.aggregate_block_and_participation(
            summaries, self._outer_dist2(summaries, axis_name),
            axis_name=axis_name, key=self._outer_key(key),
        )
        if outer_part is None:
            return agg, None
        if inner_part is None:
            # coordinate-wise inner rules select per coordinate, not per
            # worker: within a group the weight is uniform
            inner_part = jnp.full((self.nb_groups, self.g), 1.0 / self.g, jnp.float32)
        participation = (outer_part[:, None] * inner_part).reshape(self.nb_workers)
        return agg, participation


register("hier", HierarchicalGAR)

"""``tree`` — the L-level aggregation-tree meta-GAR (in-graph plane).

``hier`` (gars/hierarchical.py) is the 2-level special case; ``tree``
generalizes it to any depth and adds the topology subsystem's concerns
(aggregathor_tpu/topology/):

- per-level rules drawn from the live registry, f-budgets COMPOSED through
  the levels at parse time (topology/spec.py owns the arithmetic:
  ``b_{l+1} = min(b_l, m_l) + agg_f_l``, a Byzantine parent corrupts at
  most one outer row);
- the PR-14 wire codec on every inter-level ``link`` — each level's
  summaries take a traced encode/decode round trip before the next rule
  sees them, so in-graph numerics match what the host-plane
  sub-aggregators actually ship (and the tree multiplies the wire win:
  ``sum(m_l)`` rows cross compressed links every round instead of one);
- ``redundancy``/``agg-f`` declarations that size the HOST plane
  (topology/tree.py: shadow reconstruction, custody chain, per-level
  bounded wait) — honest shadows compute bit-identical summaries, so the
  in-graph function is the r-fold-replicated tree's numerics already.

Spec grammar (full reference: topology/spec.py)::

    tree:g=16x4,rules=median>trimmed-mean>krum,link=int8,redundancy=2,agg-f=1x0

**NaN rows.**  A NaN leaf row is absorbed by the first tolerant level on
its root path; a fully-NaN group (a whole excluded subtree) NaN-poisons
every rule's summary — average and median alike — so the exclusion
propagates upward to the first level where a tolerant rule can drop it as
ONE row.  ``nan_row_tolerant`` is declared the hier way: any tolerant
level makes the tree tolerant (per-level capacity is bounded by that
level's feasibility, which parse-time composition already enforces).

**Keys.**  Per-group streams at level l derive from ``fold_in(key, l)``
folded with the group index; the root uses ``fold_in(key, L + 1)`` — all
disjoint, and exactly hier's layout at L=1 (inner=fold_in 1, outer=2).

**Participation.**  Composes level by level like hier's: each level
scatters its rows' weights through its groups' inner weights (uniform
1/g_l fallback for coordinate-wise rules), so the (n,) vector sums to 1.
"""

import jax
import jax.numpy as jnp

from . import GAR, register
from .common import centered_gram_sq_distances


class TreeGAR(GAR):
    coordinate_wise = False
    needs_distances = False  # distances (if any) are per level, computed here
    uses_axis = True
    uses_key = True
    # must mirror topology.spec.TREE_ARG_DEFAULTS (the import is lazy —
    # topology/tree.py reaches back through parallel/ into this package,
    # and gars/__init__'s import_directory runs this module mid-init);
    # tests/test_topology.py asserts the two dicts stay equal
    ARG_DEFAULTS = {
        "g": "4",
        "rules": "median>krum",
        "link": "f32",
        "redundancy": 1,
        "agg-f": "0",
    }

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        from ..topology.spec import TreeSpec

        self.spec = TreeSpec(nb_workers, nb_byz_workers, self.args)
        self.nan_row_tolerant = any(
            r.nan_row_tolerant for r in self.spec.rules
        ) or self.spec.root_rule.nan_row_tolerant

    # ------------------------------------------------------------------ #

    def _link_roundtrip(self, summaries):
        """The inter-level wire: what a sub-aggregator ships is what the
        next level aggregates.  Traced in-graph (compress.py codecs are
        vmappable), so the fused path and the host plane agree bit-wise."""
        spec = self.spec
        if spec.link_codec is not None:
            return spec.link_codec.roundtrip_rows(summaries)
        if spec.link_dtype is not None:
            return summaries.astype(spec.link_dtype).astype(summaries.dtype)
        return summaries

    def _level_call(self, level, rows, axis_name, key, with_participation):
        """One level: (m_{l-1}, d_block) rows -> (m_l, d_block) summaries
        (+ per-group (m_l, g_l) participation when requested)."""
        rule = self.spec.rules[level]
        g = self.spec.group_sizes[level]
        nb_groups = rows.shape[0] // g
        grouped = rows.reshape(nb_groups, g, rows.shape[-1])
        dist2 = None
        if rule.needs_distances:
            partial = jax.vmap(centered_gram_sq_distances)(
                grouped.astype(jnp.float32)
            )
            if axis_name is not None:
                partial = jax.lax.psum(partial, axis_name)
            dist2 = jnp.maximum(partial, 0.0)
        keys = None
        if key is not None:
            base = jax.random.fold_in(key, level + 1)
            keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
                jnp.arange(nb_groups)
            )

        def one(block, d2, k):
            if with_participation:
                return rule.aggregate_block_and_participation(
                    block, d2, axis_name=axis_name, key=k
                )
            return rule._call_aggregate(block, d2, axis_name=axis_name, key=k), None

        in_axes = (0, 0 if dist2 is not None else None, 0 if keys is not None else None)
        summaries, part = jax.vmap(one, in_axes=in_axes)(grouped, dist2, keys)
        if part is None and with_participation:
            part = jnp.full((nb_groups, g), 1.0 / g, jnp.float32)
        return self._link_roundtrip(summaries), part

    def _root_dist2(self, summaries, axis_name):
        if not self.spec.root_rule.needs_distances:
            return None
        partial = centered_gram_sq_distances(summaries.astype(jnp.float32))
        if axis_name is not None:
            partial = jax.lax.psum(partial, axis_name)
        return jnp.maximum(partial, 0.0)

    def _root_key(self, key):
        return None if key is None else jax.random.fold_in(
            key, self.spec.nb_levels + 2
        )

    # ------------------------------------------------------------------ #

    def aggregate_block(self, block, dist2=None, axis_name=None, key=None):
        rows = block
        for level in range(self.spec.nb_levels):
            rows, _ = self._level_call(level, rows, axis_name, key, False)
        return self.spec.root_rule._call_aggregate(
            rows, self._root_dist2(rows, axis_name),
            axis_name=axis_name, key=self._root_key(key),
        )

    def aggregate_block_and_participation(self, block, dist2=None,
                                          axis_name=None, key=None):
        rows = block
        level_parts = []
        for level in range(self.spec.nb_levels):
            rows, part = self._level_call(level, rows, axis_name, key, True)
            level_parts.append(part)
        agg, root_part = self.spec.root_rule.aggregate_block_and_participation(
            rows, self._root_dist2(rows, axis_name),
            axis_name=axis_name, key=self._root_key(key),
        )
        if root_part is None:
            return agg, None
        # scatter root weights back down: at each level a group's weight
        # distributes through its members' within-group weights
        weights = root_part
        for part in reversed(level_parts):
            weights = (weights[:, None] * part).reshape(-1)
        return agg, weights


register("tree", TreeGAR)

"""Divide-and-Conquer (DnC) GAR (Shejwalkar & Houmansadr, NDSS 2021,
"Manipulating the Byzantine: Optimizing Model Poisoning Attacks and
Defenses for Federated Learning").

An extension beyond the reference's rule set — empirically among the
strongest known defenses: colluding attacks concentrate along a common
direction, so project the centered gradients onto their top singular
direction and drop the rows with the largest squared projections,

    C = G - mean(G);   v = top right-singular vector of C
    s_i = (C_i · v)²;  drop the ``remove`` largest s_i;  average the rest.

TPU formulation (exact, never materializing a (d,) singular vector): with
C = UΣVᵀ, the (n, n) Gram K = CCᵀ = UΣ²Uᵀ is one MXU matmul (psum-completed
across dimension blocks under ``uses_axis``), the top eigenvector u of K
comes from a fixed number of replicated O(n²) power-iteration steps, and
the outlier scores are s_i = λ·u_i² — no d-sized spectral work at all.
The paper subsamples coordinates to make the spectral step affordable;
the Gram trick makes it exact instead.

Non-finite rows (lossy links) are excluded up front: weight 0, zero-filled
in the mean/Gram, +inf score, and OUTSIDE the removal budget (``remove``
counts live outliers, so a lossy worker never shields a colluder).  Final
averaging weights double as per-worker participation for the suspicion
diagnostics.

Regime note: with no attack the centered spectrum is flat and the top
singular direction of pure noise is ill-defined — which honest rows get
dropped is then arbitrary (and precision-sensitive), though the kept mean
stays an unbiased honest average.  Under a genuine colluding signal the
spectrum is decisive and the selection is stable (tests/test_gars.py
``test_dnc_regime_properties``).
"""

import jax
import jax.numpy as jnp

from . import GAR, register
from .common import alive_rows, smallest_k_mask


def dnc(rows, nb_remove, iters, axis_name=None):
    """DnC over the (n, d_block) rows; returns ``(mean, participation)``."""
    alive, safe = alive_rows(rows, axis_name)
    nb_alive = jnp.maximum(jnp.sum(alive), 1.0)
    mean = jnp.sum(safe, axis=0) / nb_alive  # safe is already zero-filled
    centered = (safe - mean[None, :]) * alive[:, None]
    # (n, n) Gram of the centered rows, completed across dimension blocks.
    gram = jax.lax.dot_general(
        centered, centered, (((1,), (1,)), ((), ())), precision=jax.lax.Precision.HIGHEST
    )
    if axis_name is not None:
        gram = jax.lax.psum(gram, axis_name)
    # Replicated O(n²) power iteration for the top eigenvector of K = CCᵀ.
    # Init from diag(K) = ||C_i||², NOT the ones vector: 1 is EXACTLY in K's
    # null space (1ᵀC = 0 by mean-centering), so a ones start would converge
    # only via rounding residue.  The diagonal is Σ_j λ_j·(u_j∘u_j), which
    # generically carries a top-eigenvector component.
    u = jnp.diagonal(gram)
    u = u / jnp.maximum(jnp.linalg.norm(u), 1e-30)
    for _ in range(iters):
        u = gram @ u
        u = u / jnp.maximum(jnp.linalg.norm(u), 1e-30)
    lam = u @ (gram @ u)
    # Outlier scores s_i = λ·u_i² = (C_i · v)².  Dead rows score +inf and are
    # excluded OUTSIDE the removal budget: ``nb_remove`` counts live
    # outliers, so a lossy worker never shields a colluder from removal
    # (k = nb_alive - nb_remove is data-dependent; the rank mask accepts a
    # traced threshold).
    scores = jnp.where(alive > 0.0, lam * u * u, jnp.inf)
    kept = smallest_k_mask(scores, nb_alive - nb_remove).astype(jnp.float32) * alive
    weights = kept / jnp.maximum(jnp.sum(kept), 1.0)
    return jnp.sum(weights[:, None] * safe, axis=0), weights


class DnCGAR(GAR):
    coordinate_wise = False
    needs_distances = False
    nan_row_tolerant = True  # dead rows excluded outside the removal budget
    uses_axis = True  # exact blockwise Gram via one psum
    ARG_DEFAULTS = {"remove": -1, "iters": 8}

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        from ..utils import UserException

        self.nb_remove = int(self.args["remove"])
        if self.nb_remove < 0:
            self.nb_remove = self.nb_byz_workers  # the paper's c·f with c = 1
        self.iters = int(self.args["iters"])
        if self.iters < 1:
            raise UserException("dnc needs iters >= 1")
        if not 0 <= self.nb_remove < self.nb_workers:
            raise UserException(
                "dnc must keep at least one worker (n=%d, remove=%d)"
                % (self.nb_workers, self.nb_remove)
            )
        if self.nb_workers <= 2 * self.nb_byz_workers:
            from ..utils import warning

            warning("dnc tolerates f < n/2; n=%d f=%d is out of bound"
                    % (self.nb_workers, self.nb_byz_workers))

    def aggregate_block(self, block, dist2=None, axis_name=None):
        agg, _ = dnc(block, self.nb_remove, self.iters, axis_name)
        return agg

    def aggregate_block_and_participation(self, block, dist2=None, axis_name=None, key=None):
        return dnc(block, self.nb_remove, self.iters, axis_name)


register("dnc", DnCGAR)

"""Centered-clipping GAR (Karimireddy, He, Jaggi 2021, "Learning from
History for Byzantine Robust Optimization").

An extension beyond the reference's rule set: iteratively re-estimate the
center ``v`` by averaging *clipped* deviations,

    v  <-  v + (1/n) sum_i  (g_i - v) * min(1, tau / |g_i - v|),

a fixed number of iterations from the coordinate-wise median.  Honest
gradients move the center; Byzantine ones contribute at most ``tau`` of
displacement each, so the estimator tolerates up to f < n/2 attackers with
a bias bounded by tau — and unlike Krum/Bulyan it needs NO pairwise
distances (O(n·d) per iteration, bandwidth-bound, ideal on TPU).

TPU mapping: each iteration is one norm reduction + one axpy over the
(n, d) matrix.  The rule declares ``uses_axis``: on the dimension-sharded
engine the per-row norms (and row finiteness) are completed with one O(n)
``psum`` per iteration, so the blockwise result is EXACTLY the dense one —
no block-local approximation.

Non-finite rows clip to radius tau in an arbitrary direction would poison
the center, so rows with any non-finite coordinate are excluded from every
iteration (their clipped contribution is zero) — the NaN-absorbing behavior
of average-nan, which this rule generalizes.
"""

import jax.numpy as jnp

from . import GAR, register
from .common import alive_rows, global_row_sq_norms, masked_coordinate_median


def centered_clip(rows, tau, iters, axis_name=None):
    """Iterative clipped-deviation center of the (n, d_block) rows.

    With ``axis_name`` the row norms and row finiteness psum across
    dimension blocks, making the blockwise result identical to dense."""
    alive, safe = alive_rows(rows, axis_name)
    nb_alive = jnp.maximum(jnp.sum(alive), 1.0)
    center = masked_coordinate_median(rows, alive)
    for _ in range(iters):
        deviation = safe - center[None, :]
        norms = jnp.sqrt(global_row_sq_norms(deviation, axis_name))[:, None]
        scale = jnp.minimum(1.0, tau / jnp.maximum(norms, 1e-12))
        clipped = deviation * scale * alive[:, None]
        center = center + jnp.sum(clipped, axis=0) / nb_alive
    return center


class CenteredClipGAR(GAR):
    coordinate_wise = False
    needs_distances = False
    nan_row_tolerant = True  # dead rows contribute zero clipped deviation
    uses_axis = True  # exact blockwise norms via one psum per iteration
    ARG_DEFAULTS = {"tau": 10.0, "iters": 3}

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        self.tau = float(self.args["tau"])
        self.iters = int(self.args["iters"])
        from ..utils import UserException

        if self.tau <= 0 or self.iters < 1:
            raise UserException("centered-clip needs tau > 0 and iters >= 1")
        if self.nb_workers <= 2 * self.nb_byz_workers:
            from ..utils import warning

            warning("centered-clip tolerates f < n/2; n=%d f=%d is out of bound"
                    % (self.nb_workers, self.nb_byz_workers))

    def aggregate_block(self, block, dist2=None, axis_name=None):
        return centered_clip(block, self.tau, self.iters, axis_name)


register("centered-clip", CenteredClipGAR)

"""Bulyan (of Multi-Krum) GAR.

Reference: aggregators/bulyan.py:43-84 and native/op_bulyan/cpu.cpp:52-188.
With m = n - f - 2, t = n - 2f - 2, b = t - 2f:

1. Krum scoring pass with **distance pruning**: for each worker i only its
   ``n - f - 2`` smallest distances contribute to score(i); the others are
   zeroed so scores can be updated in O(n) when a worker is removed
   (cpu.cpp:67-133).
2. Selection loop, ``t`` rounds: round k emits the average of the
   ``m - k`` smallest-scoring gradients (a Multi-Krum output), then removes
   the single best-scoring gradient and decrements every score by its pruned
   distance to the removed one (cpu.cpp:134-161).
3. Averaged-median coordinate-wise over the t selections: median, then the
   mean of the ``b`` values closest to it (cpu.cpp:163-187).

TPU formulation: the reference's pruning trick is a *CPU* optimization
(avoids re-sorting); here it is kept because it also makes every round's
score update a vector subtraction.  All t selection rows are emitted as one
(t, n) weight matrix, so the gradient-sized work is a single
(t, n) x (n, d) MXU matmul plus the coordinate-wise phase — both of which
apply unchanged to dimension-sharded column blocks.
"""

import jax
import jax.numpy as jnp

from . import GAR, register
from .averaged_median import averaged_median_columns
from .common import memo_by_identity, nonfinite_to_inf, select_combine, selection_mean_weights


class BulyanGAR(GAR):
    needs_distances = True
    nan_row_tolerant = True  # as krum: +inf distances, never selected

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        n, f = self.nb_workers, self.nb_byz_workers
        self.nb_multikrum = n - f - 2       # m
        self.nb_selections = n - 2 * f - 2  # t
        self.nb_closest = self.nb_selections - 2 * f  # b
        if self.nb_closest < 1:
            from ..utils import UserException

            raise UserException("bulyan needs n >= 4f + 3 (got n=%d, f=%d)" % (n, f))

    @memo_by_identity
    def selection_weights(self, dist2):
        """(t, n) weight matrix: row k averages the (m - k) smallest-scoring
        workers after k removals, reproducing the reference's selection loop."""
        n, f = self.nb_workers, self.nb_byz_workers
        in_score = n - f - 2
        clean = nonfinite_to_inf(dist2)
        clean = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, clean)
        # Row-wise distance pruning: keep each row's in_score smallest
        # (ties to the lower column index), zero the rest (cpu.cpp:102-133).
        # Rank via stable argsort-of-argsort — a stable ascending sort places
        # equal values in column-index order, so ranks[i, j] equals the count
        # of columns strictly smaller (or equal with lower index) that the
        # previous (n, n, n) comparison tensor computed, at O(n^2 log n) time
        # and O(n^2) memory instead of a 2 GB cube at n=1024.
        order = jnp.argsort(clean, axis=-1, stable=True)
        ranks = jnp.argsort(order, axis=-1)  # inverse permutation = ranks
        pruned = jnp.where(ranks < in_score, clean, 0.0)
        scores = jnp.sum(pruned, axis=-1)

        # Selection loop as a lax.scan: the trace/compile cost stays FLAT in
        # t (= n - 2f - 2), where the previous trace-time unrolling grew the
        # graph by t copies of the O(n²) rank mask — prohibitive at the
        # reference-plausible n = 512-1024, whose C++ loop had no such limit
        # (op_bulyan/cpu.cpp:134-161).  The final round's carry update is
        # computed and discarded (the reference guards it with k+1 < t; the
        # scan output is identical since only the emitted rows matter).
        def one_round(live, k):
            row = selection_mean_weights(live, self.nb_multikrum - k)
            best = jnp.argmin(nonfinite_to_inf(live))
            nxt = (live - jnp.take(pruned, best, axis=1)).at[best].set(jnp.inf)
            return nxt, row

        _, rows = jax.lax.scan(
            one_round, scores, jnp.arange(self.nb_selections))
        return rows

    def aggregate_block(self, block, dist2=None):
        assert dist2 is not None, "bulyan requires the pairwise distance matrix"
        selections = select_combine(self.selection_weights(dist2), block)
        return averaged_median_columns(selections, self.nb_selections, self.nb_closest)

    def worker_participation(self, dist2):
        # Mean over the t Krum-selection rounds of each worker's averaging
        # weight: a worker every round excludes ends at exactly 0.
        return jnp.mean(self.selection_weights(dist2), axis=0)


register("bulyan", BulyanGAR)
# Reference tier aliases (bulyan-py/co, aggregators/bulyan.py:92-97)
register("bulyan-py", BulyanGAR)
register("bulyan-co", BulyanGAR)

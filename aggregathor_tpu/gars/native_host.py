"""Native-tier GAR registrations (``*-native``).

The reference exposes each rule in several independently implemented tiers
and registers the native ones only when the toolchain builds them
(aggregators/krum.py:166-169).  Same here: every ``<rule>-native`` name wraps
the C++ host library (ops/native) for the dense ``aggregate`` path, and is
only registered when the library compiles on this host.

The blockwise path (``aggregate_block``, used by the sharded engine) is
inherited from the jnp tier: on-device aggregation is XLA's job — the native
tier exists for host-side aggregation, CPU-only deployments, and as a second
independent implementation for cross-checking (SURVEY.md §4 point 3).

Inside ``jit`` the host call is bridged with ``jax.pure_callback``.

Names register unconditionally; the C++ build/load is deferred to the first
``instantiate`` of a native rule, so importing the package never spawns a
compiler — a ``UserException`` at construction reports a missing toolchain.
"""

import numpy as np

from . import register
from .average import AverageGAR
from .average_nan import AverageNaNGAR
from .averaged_median import AveragedMedianGAR
from .bulyan import BulyanGAR
from .krum import KrumGAR
from .median import MedianGAR
from ..ops import native


def _host_dtype(dtype):
    return np.dtype(dtype) if np.dtype(dtype) in (np.float32, np.float64) else np.dtype(np.float64)


class _NativeMixin:
    """Defers the C++ build/load to rule construction time."""

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        try:
            native.load()
        except Exception as exc:
            from ..utils import UserException

            raise UserException(
                "%s requires the native GAR library: %s" % (type(self).__name__, exc)
            ) from exc


def _dense(host_fn):
    """Build an ``aggregate`` running ``host_fn(self, np_grads) -> (d,)``.

    numpy input runs directly; jax input (traced or concrete) goes through
    ``pure_callback`` so the native tier composes with jit.
    """

    def aggregate(self, grads, key=None):
        if isinstance(grads, np.ndarray):
            return host_fn(self, grads)
        import jax

        dtype = _host_dtype(grads.dtype)
        result = jax.ShapeDtypeStruct((grads.shape[1],), dtype)
        return jax.pure_callback(
            lambda g: host_fn(self, np.asarray(g, dtype=dtype)), result, grads
        )

    return aggregate


class NativeAverageGAR(_NativeMixin, AverageGAR):
    aggregate = _dense(lambda self, g: native.average(g))


class NativeAverageNaNGAR(_NativeMixin, AverageNaNGAR):
    aggregate = _dense(lambda self, g: native.average_nan(g))


class NativeMedianGAR(_NativeMixin, MedianGAR):
    aggregate = _dense(lambda self, g: native.median(g))


class NativeAveragedMedianGAR(_NativeMixin, AveragedMedianGAR):
    aggregate = _dense(lambda self, g: native.averaged_median(g, self.nb_byz_workers))


class NativeKrumGAR(_NativeMixin, KrumGAR):
    aggregate = _dense(
        lambda self, g: native.krum(g, self.nb_byz_workers, self.nb_selected)
    )


class NativeBulyanGAR(_NativeMixin, BulyanGAR):
    aggregate = _dense(lambda self, g: native.bulyan(g, self.nb_byz_workers))


register("average-native", NativeAverageGAR)
register("average-nan-native", NativeAverageNaNGAR)
register("median-native", NativeMedianGAR)
register("averaged-median-native", NativeAveragedMedianGAR)
register("krum-native", NativeKrumGAR)
register("bulyan-native", NativeBulyanGAR)

"""Coordinate-wise mean ignoring non-finite coordinates.

Exists to absorb the NaNs injected by a lossy transport on packet loss
(reference: aggregators/average-nan.py:40-68 and the UDP NaN infill at
tf_patches/patches/mpi_rendezvous_mgr.patch:833-841).

Semantics per coordinate: mean of the finite values.  When *every* worker's
coordinate is non-finite the reference's C++ computes 0/0 = NaN
(deprecated_native/native.cpp:756-782); we deliberately output 0 instead — a
NaN there would poison the parameters, and the case only arises when all n
workers lose the same region.  The numpy oracle encodes the same choice.
"""

import jax.numpy as jnp

from . import GAR, register


class AverageNaNGAR(GAR):
    coordinate_wise = True
    nan_row_tolerant = True

    def aggregate_block(self, block, dist2=None):
        finite = jnp.isfinite(block)
        total = jnp.sum(jnp.where(finite, block, 0.0), axis=0)
        count = jnp.sum(finite, axis=0)
        return jnp.where(count > 0, total / jnp.maximum(count, 1), 0.0)


register("average-nan", AverageNaNGAR)

"""Geometric-median GAR (RFA — Pillutla, Kakade, Harchaoui 2022, "Robust
Aggregation for Federated Learning").

An extension beyond the reference's rule set: the aggregate is the point
minimizing the sum of Euclidean distances to the worker gradients,

    z* = argmin_z  sum_i |g_i - z|,

approximated by a fixed number of Weiszfeld iterations from the
coordinate-wise median,

    w_i <- 1 / max(|g_i - z|, eps),    z <- sum_i w_i g_i / sum_i w_i.

Breakdown point 1/2: any minority coalition (f < n/2) moves the estimate
by a bounded amount regardless of forgery magnitude.  Like centered-clip it
needs NO pairwise distance matrix — O(n·d) per iteration, bandwidth-bound.

TPU mapping: each iteration is one row-norm reduction plus one weighted
row combine, both MXU/VPU-friendly.  On dimension-sharded engines the
per-row squared norms are completed with one O(n) ``psum`` per iteration
across blocks (``uses_axis``), so the blockwise result is EXACTLY the
dense one — every shard derives identical weights and the aggregate stays
replicated.

Non-finite rows (the lossy link's NaN infill) get weight 0 everywhere —
the NaN-absorbing convention of average-nan; all-rows-dead yields 0 like
an empty reassembly buffer.  The final normalized Weiszfeld weights double
as per-worker participation (a far-away forgery converges to weight ~0),
returned through ``aggregate_block_and_participation`` for the suspicion
diagnostics — in one pass, no state carried between calls.
"""

import jax.numpy as jnp

from . import GAR, register
from .common import alive_rows, global_row_sq_norms, masked_coordinate_median


def geometric_median(rows, iters, eps, axis_name=None):
    """Weiszfeld geometric median of the (n, d_block) rows.

    Returns ``(z, participation)`` — the (d_block,) estimate and the (n,)
    final normalized weights.  With ``axis_name``, row norms and row
    finiteness are completed across dimension blocks by ``psum``.
    """
    alive, safe = alive_rows(rows, axis_name)
    # Robust start: a mean init begins ~|forgery| away from the honest
    # cloud and Weiszfeld only closes that distance at a linear rate; the
    # coordinate-wise median starts inside it.
    z = masked_coordinate_median(rows, alive)
    weights = alive  # overwritten by the first iteration (iters >= 1)
    for _ in range(iters):
        sqn = global_row_sq_norms(safe - z[None, :], axis_name)
        weights = alive / jnp.maximum(jnp.sqrt(sqn), eps)
        total = jnp.maximum(jnp.sum(weights), 1e-30)
        z = jnp.sum(weights[:, None] * safe, axis=0) / total
        weights = weights / total
    return z, weights


class GeometricMedianGAR(GAR):
    coordinate_wise = False
    needs_distances = False
    nan_row_tolerant = True  # dead rows get Weiszfeld weight 0
    uses_axis = True  # exact blockwise norms via one psum per iteration
    ARG_DEFAULTS = {"iters": 8, "eps": 1e-6}

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        self.iters = int(self.args["iters"])
        self.eps = float(self.args["eps"])
        from ..utils import UserException

        if self.iters < 1 or self.eps <= 0:
            raise UserException("geometric-median needs iters >= 1 and eps > 0")
        if self.nb_workers <= 2 * self.nb_byz_workers:
            from ..utils import warning

            warning(
                "geometric-median tolerates f < n/2; n=%d f=%d is out of bound"
                % (self.nb_workers, self.nb_byz_workers)
            )

    def aggregate_block(self, block, dist2=None, axis_name=None):
        z, _ = geometric_median(block, self.iters, self.eps, axis_name)
        return z

    def aggregate_block_and_participation(self, block, dist2=None, axis_name=None, key=None):
        return geometric_median(block, self.iters, self.eps, axis_name)


register("geometric-median", GeometricMedianGAR)
register("rfa", GeometricMedianGAR)  # the rule's common literature name

"""n-sweep GAR scaling harness (schema ``aggregathor.gar.scaling.v1``).

The cost wall this PR attacks, measured instead of presumed: the flagship
rules (Krum, Bulyan) are O(n²·d) on the stacked (n, d) matrix, while the
composite tree rules (``hier``, ``bucketing`` — gars/hierarchical.py,
gars/bucketing.py) shrink the quadratic term to the group level, so their
time must grow **sublinearly in n²** where the flat rules grow ~quadratically.
This module sweeps both families over a worker-count grid at fixed d and
turns the timings into that verdict:

- for every rule the **tail exponent** ``p = log(t_hi/t_lo) / log(n_hi/n_lo)``
  over the two largest swept n (the asymptotic regime — small-n cells are
  dispatch-overhead-dominated on every backend), plus a whole-grid
  least-squares exponent for context;
- a composite rule passes when its tail exponent stays clearly below 2
  (``SUBLINEAR_EXPONENT_BAR``); the overall verdict is the conjunction over
  the composite family.  The flat rules' quadratic growth is *reported*
  (``flat_shows_quadratic``) but not gated: at benchmark scale it is plain,
  at smoke scale (tiny d on a CPU) constants hide it, and the claim under
  test is the composite family's escape, not the textbook cost of Krum.

Composite specs are generated per n so the OUTER matrix stays constant-sized
(``outer_rows`` target): ``hier:g=n/8`` keeps the expensive rule at 8 rows
while the vmapped inner pass grows linearly — total work linear in n.  The
nested ``bucketing:inner=hier(...)`` cell exercises spec-composition through
the same harness.

Timing protocol: every timed repetition is **individually synced** — the
output is ``block_until_ready``'d and a scalar of it is fetched to the host
before the clock stops — and the median rep is reported.  (The older
dispatch-loop slope estimate in benchmarks/gar_kernels.py could go negative
under backend latency jitter and clamped whole rows to 0.0 ms; see
``time_aggregate``.)

Used by ``benchmarks/gar_kernels.py --sweep-ns`` and
``scripts/run_scaling_smoke.sh``; validated by tests/test_gar_scaling.py.
"""

import json
import math
import time

import numpy as np

SCHEMA = "aggregathor.gar.scaling.v1"

#: A composite rule's tail exponent must stay below this to count as
#: "sublinear in n²" — 2.0 is the quadratic line, and the 0.5 margin keeps
#: measurement noise from waving a genuinely quadratic rule through.
SUBLINEAR_EXPONENT_BAR = 1.5

#: Informational counterpart for the flat rules: a tail exponent above this
#: reads as "the quadratic term is visible at this scale".
QUADRATIC_EXPONENT_FLOOR = 1.25

#: Target size of the outer (expensive) matrix in generated hier specs.
OUTER_ROWS = 8


def sync_fetch(out):
    """Truly wait for ``out``: ``block_until_ready`` + ONE SCALAR host fetch.

    Under the tunneled TPU backend ``block_until_ready`` returns
    immediately and only a host fetch waits for the device stream; on every
    backend, ending a timed section without either times async dispatch.
    The fetch is a single element — ``out.ravel()[0]`` runs on device and
    only the 4-byte scalar crosses to the host, so a fast kernel's timing
    is not swamped by transferring its whole (possibly many-MB) output.
    The ONE sync primitive every timed GAR section uses (here,
    benchmarks/gar_kernels.py, and the runner's ``--gar-probe``)."""
    import jax

    jax.block_until_ready(out)
    leaf = jax.tree_util.tree_leaves(out)[0]
    # device gather of one element + 4 B host fetch (a plain host index on
    # the native tier's numpy outputs)
    float(leaf.ravel()[0])


def time_aggregate(fn, reps):
    """Median per-call ms; EVERY timed output fully synced (sync_fetch of
    that rep's own output).

    The median over reps is jitter-robust and cannot go negative — unlike a
    ``t_many - t_one`` slope, which produced the 0.0 ms ``dnc`` rows in
    benchmarks/resume_gar_kernels.json.  The fetch adds one scalar
    roundtrip per rep, which the kernels under test dwarf.
    """
    sync_fetch(fn())  # warmup: compile + first sync
    times = []
    for _ in range(max(1, int(reps))):
        begin = time.perf_counter()
        sync_fetch(fn())
        times.append(time.perf_counter() - begin)
    times.sort()
    return times[len(times) // 2] * 1e3


def hier_spec(n, outer="krum", inner="median", outer_rows=OUTER_ROWS):
    """The per-n hier spec holding the outer matrix at ``outer_rows`` rows
    (g = n/outer_rows, clamped to a divisor of n — total work linear in n)."""
    g = max(1, n // outer_rows)
    while n % g:
        g -= 1
    return "hier:g=%d,inner=%s,outer=%s" % (g, inner, outer)


def nested_spec(n, outer="krum", outer_rows=OUTER_ROWS):
    """bucketing-over-hier: s=2 bucketing feeding a hier inner — the
    spec-composition cell (parenthesized sub-spec, gars/__init__.parse_spec)."""
    buckets = n // 2
    g = max(1, buckets // outer_rows)
    while buckets % g:
        g -= 1
    return "bucketing:s=2,inner=hier(g=%d,inner=median,outer=%s)" % (g, outer)


def default_rules(f):
    """The swept rule family: (name, kind, flat_ref, spec_fn(n) -> spec)."""
    del f  # the defaults are feasible at every swept n for small f
    return [
        ("krum", "flat", None, lambda n: "krum"),
        ("bulyan", "flat", None, lambda n: "bulyan"),
        ("hier-krum", "composite", "krum", lambda n: hier_spec(n, outer="krum")),
        ("hier-bulyan", "composite", "bulyan", lambda n: hier_spec(n, outer="bulyan")),
        ("bucketing-hier-krum", "composite", "krum", nested_spec),
    ]


def _fit_exponent(ns, ms):
    """Least-squares slope of log(ms) vs log(n) over the whole grid."""
    xs = np.log(np.asarray(ns, np.float64))
    ys = np.log(np.maximum(np.asarray(ms, np.float64), 1e-9))
    xs = xs - xs.mean()
    return float(np.dot(xs, ys - ys.mean()) / max(np.dot(xs, xs), 1e-12))


def _tail_exponent(ns, ms):
    """Local exponent over the two largest n — the asymptotic claim."""
    return float(
        math.log(max(ms[-1], 1e-9) / max(ms[-2], 1e-9)) / math.log(ns[-1] / ns[-2])
    )


def run_sweep(ns, d, f=1, reps=5, rules=None, progress=None):
    """Sweep rules over worker counts at fixed d; returns the scaling doc.

    Every cell jits ONE rule-only aggregation at (n, d) — the same
    measurement instrument as the engines' ``build_gar_probe`` — and times
    it with the per-rep-synced protocol above.  ``rules`` defaults to
    :func:`default_rules`; entries are (name, kind, flat_ref, spec_fn).
    """
    import jax

    from . import instantiate

    # dedup AND sort: duplicate worker counts would both waste cells and
    # zero the log(n_hi/n_lo) denominator in _tail_exponent
    ns = sorted({int(n) for n in ns})
    if len(ns) < 2:
        raise ValueError(
            "the n-sweep needs at least two distinct worker counts, got %r" % (ns,)
        )
    rules = default_rules(f) if rules is None else rules
    d = int(d)
    key = jax.random.PRNGKey(0)
    # n is the OUTER loop: one seeded device-resident fixture per n, shared
    # by every rule, then released before the next n — peak device memory is
    # max(ns)*d, not sum(ns)*d.  (f32 generation: an f64 .normal would also
    # transiently double the host footprint.)
    ms_cells, spec_cells = {}, {}
    for n in ns:
        rows = jax.device_put(
            np.random.default_rng(n).standard_normal(size=(n, d), dtype=np.float32)
        )
        for name, kind, flat_ref, spec_fn in rules:
            spec = spec_fn(n)
            spec_cells[(name, n)] = spec
            gar = instantiate(spec, n, f)
            # gar.aggregate(grads, key=None) is the uniform dense-tier entry:
            # _call_aggregate forwards the key only to rules declaring uses_key
            agg = jax.jit(gar.aggregate)
            cell_ms = time_aggregate(lambda: agg(rows, key), reps)
            ms_cells[(name, n)] = round(cell_ms, 4)
            if progress is not None:
                progress("%-22s n=%-4d %10.3f ms  (%s)" % (name, n, cell_ms, spec))
    entries = []
    for name, kind, flat_ref, spec_fn in rules:
        ms_by_n = [ms_cells[(name, n)] for n in ns]
        entry = {
            "rule": name,
            "kind": kind,
            "spec_by_n": {str(n): spec_cells[(name, n)] for n in ns},
            "ms": ms_by_n,
            "tail_exponent": round(_tail_exponent(ns, ms_by_n), 3),
            "fit_exponent": round(_fit_exponent(ns, ms_by_n), 3),
        }
        if kind == "composite":
            entry["flat_ref"] = flat_ref
            entry["sublinear_in_n2"] = entry["tail_exponent"] < SUBLINEAR_EXPONENT_BAR
        entries.append(entry)

    by_name = {e["rule"]: e for e in entries}
    for entry in entries:
        ref = by_name.get(entry.get("flat_ref"))
        if ref is not None:
            entry["speedup_at_nmax"] = round(
                max(ref["ms"][-1], 1e-9) / max(entry["ms"][-1], 1e-9), 3
            )
    composites = [e for e in entries if e["kind"] == "composite"]
    flats = [e for e in entries if e["kind"] == "flat"]
    verdict = {
        # the gated claim: every composite rule escapes the n² wall
        "composite_sublinear_in_n2": all(e["sublinear_in_n2"] for e in composites),
        # informational: does this scale/backend show the flat rules'
        # quadratic term at all? (tiny-d CPU smokes legitimately may not)
        "flat_shows_quadratic": any(
            e["tail_exponent"] > QUADRATIC_EXPONENT_FLOOR for e in flats
        ),
    }
    verdict["ok"] = verdict["composite_sublinear_in_n2"]
    return {
        "schema": SCHEMA,
        "platform": jax.devices()[0].platform,
        "ns": ns,
        "d": d,
        "f": int(f),
        "reps": int(reps),
        "sublinear_exponent_bar": SUBLINEAR_EXPONENT_BAR,
        "rules": entries,
        "verdict": verdict,
    }


def validate_scaling_doc(doc):
    """Schema contract for ``aggregathor.gar.scaling.v1`` (shared by
    tests/test_gar_scaling.py and scripts/run_scaling_smoke.sh); raises
    AssertionError with a field-naming message on violation."""
    assert doc.get("schema") == SCHEMA, "schema != %s: %r" % (SCHEMA, doc.get("schema"))
    ns = doc.get("ns")
    assert isinstance(ns, list) and len(ns) >= 2, "ns must list >= 2 worker counts"
    assert ns == sorted(ns) and all(
        isinstance(n, int) and n >= 1 for n in ns
    ), "ns must be ascending positive ints"
    for field in ("d", "f", "reps"):
        assert isinstance(doc.get(field), int) and doc[field] >= 0, field
    assert isinstance(doc.get("platform"), str) and doc["platform"], "platform"
    rules = doc.get("rules")
    assert isinstance(rules, list) and rules, "rules must be a nonempty list"
    kinds = set()
    for entry in rules:
        name = entry.get("rule")
        assert isinstance(name, str) and name, "rule name"
        assert entry.get("kind") in ("flat", "composite"), "%s: kind" % name
        kinds.add(entry["kind"])
        ms = entry.get("ms")
        assert isinstance(ms, list) and len(ms) == len(ns), "%s: ms misaligned with ns" % name
        assert all(
            isinstance(v, (int, float)) and v > 0 and math.isfinite(v) for v in ms
        ), "%s: ms must be positive finite (0.0 means an unsynced timer)" % name
        spec_by_n = entry.get("spec_by_n")
        assert isinstance(spec_by_n, dict) and set(spec_by_n) == {
            str(n) for n in ns
        }, "%s: spec_by_n keys" % name
        for field in ("tail_exponent", "fit_exponent"):
            assert isinstance(entry.get(field), (int, float)) and math.isfinite(
                entry[field]
            ), "%s: %s" % (name, field)
        if entry["kind"] == "composite":
            assert isinstance(entry.get("flat_ref"), str), "%s: flat_ref" % name
            assert isinstance(entry.get("sublinear_in_n2"), bool), (
                "%s: sublinear_in_n2" % name
            )
    assert kinds == {"flat", "composite"}, "sweep needs both flat and composite rules"
    verdict = doc.get("verdict")
    assert isinstance(verdict, dict), "verdict"
    for field in ("composite_sublinear_in_n2", "flat_shows_quadratic", "ok"):
        assert isinstance(verdict.get(field), bool), "verdict.%s" % field
    want = all(e["sublinear_in_n2"] for e in rules if e["kind"] == "composite")
    assert verdict["composite_sublinear_in_n2"] == want, (
        "verdict.composite_sublinear_in_n2 inconsistent with per-rule flags"
    )
    assert verdict["ok"] == verdict["composite_sublinear_in_n2"], "verdict.ok"
    return doc


def render_table(doc):
    """Human-readable sweep table (one line per rule x n, plus the verdict)."""
    lines = ["%-22s %-9s %6s %12s %8s" % ("rule", "kind", "n", "ms", "exp")]
    for entry in doc["rules"]:
        for n, ms in zip(doc["ns"], entry["ms"]):
            lines.append(
                "%-22s %-9s %6d %12.3f %8s"
                % (entry["rule"], entry["kind"], n, ms,
                   "p=%.2f" % entry["tail_exponent"] if n == doc["ns"][-1] else "")
            )
    verdict = doc["verdict"]
    lines.append(
        "verdict: composite sublinear in n^2: %s; flat quadratic visible: %s"
        % ("YES" if verdict["composite_sublinear_in_n2"] else "NO",
           "yes" if verdict["flat_shows_quadratic"] else "no (scale too small)")
    )
    return "\n".join(lines)


def save_doc(path, doc):
    with open(path, "w") as fd:
        json.dump(doc, fd, indent=2, sort_keys=True)
        fd.write("\n")

"""Bucketing meta-GAR (Karimireddy, He, Jaggi 2022, "Byzantine-Robust
Learning on Heterogeneous Datasets via Bucketing").

An extension beyond the reference's rule set, pointed at by the retrieved
meta-aggregation literature (PAPERS.md): randomly permute the n workers,
average disjoint buckets of ``s``, and hand the n/s bucket means to any
inner GAR,

    buckets = mean over groups of s of  g_{pi(1)} ... g_{pi(n)}
    output  = inner_gar(buckets)

Bucket means have s-times lower variance, so honest heterogeneity (non-iid
worker data) no longer looks Byzantine to the inner rule — the failure mode
plain Krum/median provably hit on heterogeneous data.  Each Byzantine
worker corrupts at most one bucket, so the inner rule runs with the same
declared ``f`` over ``n/s`` rows (its (n/s, f) feasibility is validated at
construction).

TPU mapping: one replicated permutation + a (n/s, s, d)->mean reshape —
pure VPU bandwidth — then the inner rule as usual.  The rule declares
``uses_key``: the engine feeds the replicated per-step PRNG key, so the
permutation re-draws every step (the paper's sampling) yet is identical on
every device and dimension block — replication is never broken.  Inner
pairwise distances are computed on the bucket means blockwise and completed
with one psum (``uses_axis``), exactly like the engine does for direct
distance rules.

NaN rows (lossy link): a dead worker poisons its bucket's mean, and the
inner rule's own NaN conventions then apply to that bucket row — with
``inner:krum`` a NaN bucket is never selected, so up to f lossy/Byzantine
workers still only cost f buckets.

Ragged n (s not dividing n): the permuted stack is padded with NaN rows to
the next multiple of ``s``, so the LAST bucket is always NaN-poisoned (its
mean contains padded NaN rows) and the existing NaN-row conventions absorb
it.  f-accounting: the inner rule then sees ``ceil(n/s)`` rows of which up
to ``f + 1`` are bad (f Byzantine buckets plus the one guaranteed-NaN
padding bucket), so it is instantiated with ``f + 1`` declared Byzantine
rows and MUST be NaN-row tolerant (validated at parse time) — a
non-excluding inner would let the padding poison every step.  The price of
raggedness: the ``s - (n mod s)``-padded bucket's real members are
sacrificed that step (their bucket is never selected); the per-step
permutation rotates who pays, and their scattered participation is 0, so
the (n,) participation still sums to 1.  Caveat: the rotation needs the
step key — on the keyless dense/oracle tier (``aggregate(grads)`` with no
``key``) the permutation is the identity, so the SAME trailing workers sit
in the padded bucket every call; keyless ragged use is for offline
benchmarks/oracles, not training (both engines always pass the step key).
"""

import jax
import jax.numpy as jnp

from . import GAR, instantiate, register
from .common import centered_gram_sq_distances


class BucketingGAR(GAR):
    coordinate_wise = False
    needs_distances = False  # distances (if any) are over bucket means, computed here
    uses_axis = True
    uses_key = True
    #: optional ``secure.masking.GroupMasking``: bucket means are computed in
    #: the exact mod-2^64 masked domain, individual rows one-time-padded
    #: within their bucket (set via ``secure.masking.enable_masking``)
    masking = None
    ARG_DEFAULTS = {"s": 2, "inner": "krum"}

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        from ..utils import UserException

        self.s = int(self.args["s"])
        if self.s < 1:
            raise UserException(
                "bucketing needs s >= 1 (got n=%d, s=%r)"
                % (self.nb_workers, self.args["s"])
            )
        # Ragged n: pad the permuted stack with NaN rows to the next multiple
        # of s — the padding lands in ONE always-NaN bucket (see module
        # docstring for the f-accounting).
        self.nb_padded = (-self.nb_workers) % self.s
        self.nb_buckets = (self.nb_workers + self.nb_padded) // self.s
        # The inner rule sees ceil(n/s) rows with (at most) the same f
        # Byzantine ones, plus the guaranteed-NaN padding bucket when ragged
        # — its own (n_buckets, f') feasibility check runs here, at parse time.
        inner_f = self.nb_byz_workers + (1 if self.nb_padded else 0)
        self.inner = instantiate(str(self.args["inner"]), self.nb_buckets, inner_f)
        # A NaN worker makes its whole bucket NaN; tolerance is the inner's.
        self.nan_row_tolerant = self.inner.nan_row_tolerant
        if self.nb_padded and not self.inner.nan_row_tolerant:
            raise UserException(
                "bucketing with s=%d not dividing n=%d pads with a NaN bucket "
                "every step, which inner rule %s does not cleanly exclude; "
                "pick a NaN-excluding inner rule or an s dividing n"
                % (self.s, self.nb_workers, type(self.inner).__name__)
            )

    def _buckets(self, block, key, axis_name=None):
        n, s = self.nb_workers, self.s
        perm = (
            jax.random.permutation(key, n)
            if key is not None
            else jnp.arange(n)  # dense/oracle tier without a step key
        )
        stack = block[perm]
        if self.nb_padded:
            pad = jnp.full((self.nb_padded, block.shape[-1]), jnp.nan, block.dtype)
            stack = jnp.concatenate([stack, pad], axis=0)
        grouped = stack.reshape(self.nb_buckets, s, block.shape[-1])
        if self.masking is not None:
            # Bucket-level secure aggregation (secure/masking.py): the same
            # bucket means, computed in the exact masked integer domain —
            # pairwise pads cancel mod 2^64, a dropped row NaNs its bucket
            # (uncancelled mask), and the padded ragged bucket was NaN
            # already.  fold tag 7 inside keeps the pad stream disjoint
            # from this permutation (raw key) and the inner rule (fold 1).
            from ..secure.masking import masked_group_mean

            return masked_group_mean(
                grouped, key, self.masking, axis_name=axis_name
            ), perm
        return jnp.mean(grouped, axis=1), perm

    def _inner_dist2(self, buckets, axis_name):
        if not self.inner.needs_distances:
            return None
        partial = centered_gram_sq_distances(buckets.astype(jnp.float32))
        if axis_name is not None:
            partial = jax.lax.psum(partial, axis_name)
        return jnp.maximum(partial, 0.0)

    def _inner_key(self, key):
        # A nested uses_key inner (inner:bucketing) must re-randomize too —
        # hand it a derived key, never the identity-permutation None.
        return None if key is None else jax.random.fold_in(key, 1)

    def aggregate_block(self, block, dist2=None, axis_name=None, key=None):
        buckets, _ = self._buckets(block, key, axis_name=axis_name)
        return self.inner._call_aggregate(
            buckets, self._inner_dist2(buckets, axis_name),
            axis_name=axis_name, key=self._inner_key(key),
        )

    def aggregate_block_and_participation(self, block, dist2=None, axis_name=None, key=None):
        buckets, perm = self._buckets(block, key, axis_name=axis_name)
        agg, bucket_part = self.inner.aggregate_block_and_participation(
            buckets, self._inner_dist2(buckets, axis_name),
            axis_name=axis_name, key=self._inner_key(key),
        )
        if bucket_part is None:
            return agg, None
        # Worker i inherits 1/s of its bucket's participation: scatter the
        # (ceil(n/s),) bucket weights back through the permutation.  Ragged
        # n: the padded slots sit at the END of the permuted stack, so
        # dropping the tail keeps exactly the real workers — and their
        # bucket (always-NaN, never selected by the validated NaN-tolerant
        # inner) carries weight 0, so the scatter still sums to 1.
        per_worker = jnp.repeat(bucket_part / self.s, self.s)[: self.nb_workers]
        participation = jnp.zeros(self.nb_workers, per_worker.dtype).at[perm].set(per_worker)
        return agg, participation


register("bucketing", BucketingGAR)

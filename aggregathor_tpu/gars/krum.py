"""Multi-Krum GAR.

Reference: aggregators/krum.py:45-158 and native/op_krum/cpu.cpp:53-122.
Per worker i: score(i) = sum of its ``n - f - 2`` smallest pairwise squared
distances (non-finite distance counts as +inf, krum.py:71-73); the output is
the average of the ``m = n - f - 2`` smallest-scoring gradients (krum.py:93).

TPU formulation: the (n, n) distance matrix comes from one Gram matmul
(``common.pairwise_sq_distances``); scoring is an O(n²) sort; the final
average is a (1, n) x (n, d) matmul of selection weights against the gradient
matrix — so the whole rule is MXU work plus a tiny replicated sort, and
``aggregate_block`` applies unchanged to dimension-sharded column blocks.
"""

import jax.numpy as jnp

from . import GAR, register
from .common import (
    memo_by_identity,
    nonfinite_to_inf,
    select_combine,
    selection_mean_weights,
    smallest_k_sum,
)


def krum_scores(dist2, nb_workers, nb_byz_workers):
    """(n,) Multi-Krum scores from the (n, n) squared-distance matrix."""
    clean = nonfinite_to_inf(dist2)
    clean = jnp.where(jnp.eye(nb_workers, dtype=bool), jnp.inf, clean)
    return smallest_k_sum(clean, nb_workers - nb_byz_workers - 2, axis=-1)


class KrumGAR(GAR):
    needs_distances = True
    nan_row_tolerant = True  # NaN row -> +inf distances -> never selected

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        super().__init__(nb_workers, nb_byz_workers, args)
        self.nb_selected = self.nb_workers - self.nb_byz_workers - 2
        if self.nb_selected < 1:
            from ..utils import UserException

            raise UserException("krum needs n >= f + 3 (got n=%d, f=%d)" % (nb_workers, nb_byz_workers))

    @memo_by_identity
    def selection_weights(self, dist2):
        """(n,) averaging weights over the m smallest-scoring workers."""
        scores = krum_scores(dist2, self.nb_workers, self.nb_byz_workers)
        return selection_mean_weights(scores, self.nb_selected)

    def aggregate_block(self, block, dist2=None):
        assert dist2 is not None, "krum requires the pairwise distance matrix"
        return select_combine(self.selection_weights(dist2), block)

    def worker_participation(self, dist2):
        return self.selection_weights(dist2)


register("krum", KrumGAR)
# Reference tier aliases (krum-py/tf/co, aggregators/krum.py:166-169): all map
# to the jit tier — tier choice is an XLA backend concern here, not an API one.
register("krum-py", KrumGAR)
register("krum-tf", KrumGAR)
register("krum-co", KrumGAR)

"""Coordinate-wise median GAR.

Reference: aggregators/median.py:40-68 backed by the C++ ``nth_element`` with
non-finite values ordered last (deprecated_native/native.cpp:678-704): the
median is the element at index ``n // 2`` of the ascending order with
non-finite treated as +inf (i.e. the upper median for even n).
"""

import jax.numpy as jnp

from . import GAR, register
from .common import nonfinite_to_inf


class MedianGAR(GAR):
    coordinate_wise = True

    def aggregate_block(self, block, dist2=None):
        ordered = jnp.sort(nonfinite_to_inf(block), axis=0)
        return ordered[self.nb_workers // 2]


register("median", MedianGAR)

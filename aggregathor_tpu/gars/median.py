"""Coordinate-wise median GAR.

Reference: aggregators/median.py:40-68 backed by the C++ ``nth_element`` with
non-finite values ordered last (deprecated_native/native.cpp:678-704): the
median is the element at index ``n // 2`` of the ascending order with
non-finite treated as +inf (i.e. the upper median for even n).
"""

import jax.numpy as jnp

from . import GAR, register
from .common import nonfinite_to_inf, use_pallas_coordinate_tier


def median_columns(block, nb_rows):
    """(d,) per-column upper median, non-finite ordered last.

    Returns the *original* value at the median slot (possibly NaN/inf, the
    reference returns whatever ``nth_element`` lands on — native.cpp:678-704)
    so every tier (jnp/oracle/native/pallas) agrees bit-for-bit on which
    poison value reaches the optimizer.  jnp.argsort is stable, matching the
    oracle's tie-breaking.

    On TPU, large blocks dispatch to the Pallas rank-selection kernel
    (identical selection, measured 20x faster at d=8.4M — see
    ``use_pallas_coordinate_tier``).
    """
    if block.shape[0] == nb_rows and use_pallas_coordinate_tier(block):
        from ..ops import pallas_kernels as pk

        return pk.coordinate_median(block)
    order = jnp.argsort(nonfinite_to_inf(block), axis=0)
    return jnp.take_along_axis(block, order[nb_rows // 2][None, :], axis=0)[0]


class MedianGAR(GAR):
    coordinate_wise = True
    # NOT nan_row_tolerant: NaN values sort last but still occupy order-
    # statistic slots — an unbounded number of dead rows shifts the upper
    # median toward the maximum instead of being excluded

    def aggregate_block(self, block, dist2=None):
        return median_columns(block, self.nb_workers)


register("median", MedianGAR)

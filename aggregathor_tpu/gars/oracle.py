"""Reference-faithful numpy oracle implementations of every GAR.

These mirror the algorithms of the reference's CPU kernels step by step
(aggregators/deprecated_native/native.cpp, native/op_krum/cpu.cpp,
native/op_bulyan/cpu.cpp) using plain numpy/python — slow, obvious, and used
as the ground truth by the cross-tier equivalence tests (SURVEY.md §4 point 3:
redundant implementations are the de-facto correctness oracle).

Not registered in the GAR registry: this tier exists for tests and debugging.
"""

import math

import numpy as np


def _nonfinite_last_sorted(values):
    """Ascending order with non-finite values last (native.cpp:691-697)."""
    values = np.asarray(values, dtype=np.float64)
    key = np.where(np.isfinite(values), values, np.inf)
    return values[np.argsort(key, kind="stable")]


def average(grads, f=0):
    return np.mean(np.asarray(grads, dtype=np.float64), axis=0)


def average_nan(grads, f=0):
    """Finite-only coordinate mean; all-non-finite column -> 0 (framework choice, see gars/average_nan.py)."""
    grads = np.asarray(grads, dtype=np.float64)
    finite = np.isfinite(grads)
    count = finite.sum(axis=0)
    total = np.where(finite, grads, 0.0).sum(axis=0)
    return np.where(count > 0, total / np.maximum(count, 1), 0.0)


def median(grads, f=0):
    """Upper median with non-finite last (native.cpp:678-704)."""
    grads = np.asarray(grads, dtype=np.float64)
    n, d = grads.shape
    out = np.empty(d)
    for x in range(d):
        out[x] = _nonfinite_last_sorted(grads[:, x])[n // 2]
    return out


def averaged_median(grads, f):
    """Median then mean of the beta = n - f closest-to-median (native.cpp:714-747)."""
    grads = np.asarray(grads, dtype=np.float64)
    n, d = grads.shape
    beta = n - f
    out = np.empty(d)
    for x in range(d):
        col = grads[:, x]
        med = _nonfinite_last_sorted(col)[n // 2]
        dev = np.abs(col - med)
        dev = np.where(np.isfinite(dev), dev, np.inf)
        closest = col[np.argsort(dev, kind="stable")[:beta]]
        out[x] = np.mean(closest)
    return out


def _pairwise_sq_distances(grads):
    n = grads.shape[0]
    dist = np.zeros((n, n))
    for i in range(n):
        for j in range(i + 1, n):
            delta = grads[i] - grads[j]
            d2 = float(np.sum(delta * delta))
            if math.isnan(d2):
                d2 = math.inf
            dist[i, j] = dist[j, i] = d2
    return dist


def krum_scores(grads, f):
    """Score(i) = sum of i's n-f-2 smallest pairwise squared distances (krum.py:56-87)."""
    grads = np.asarray(grads, dtype=np.float64)
    n = grads.shape[0]
    dist = _pairwise_sq_distances(grads)
    scores = np.empty(n)
    for i in range(n):
        others = np.sort(np.delete(dist[i], i))
        scores[i] = np.sum(others[: n - f - 2])
    return scores


def krum(grads, f):
    """Average of the m = n - f - 2 smallest-scoring gradients (krum.py:93)."""
    grads = np.asarray(grads, dtype=np.float64)
    n = grads.shape[0]
    m = n - f - 2
    scores = krum_scores(grads, f)
    selected = np.argsort(scores, kind="stable")[:m]
    return np.mean(grads[selected], axis=0)


def bulyan(grads, f):
    """Iterative Multi-Krum selection with pruned incremental rescoring, then
    coordinate-wise averaged-median (op_bulyan/cpu.cpp:52-188)."""
    grads = np.asarray(grads, dtype=np.float64)
    n, d = grads.shape
    m = n - f - 2
    t = n - 2 * f - 2
    b = t - 2 * f
    in_score = n - f - 2
    dist = _pairwise_sq_distances(grads)
    np.fill_diagonal(dist, np.inf)
    # Row-wise pruning: keep each row's in_score smallest distances, zero the rest
    pruned = np.zeros_like(dist)
    scores = np.empty(n)
    for i in range(n):
        order = np.argsort(np.where(np.isfinite(dist[i]), dist[i], np.inf), kind="stable")
        kept = order[:in_score]
        pruned[i, kept] = np.where(np.isfinite(dist[i, kept]), dist[i, kept], np.inf)
        scores[i] = np.sum(pruned[i, kept])
    # Selection loop
    selections = np.empty((t, d))
    live_scores = scores.copy()
    for k in range(t):
        key = np.where(np.isfinite(live_scores), live_scores, np.inf)
        order = np.argsort(key, kind="stable")
        selections[k] = np.mean(grads[order[: m - k]], axis=0)
        if k + 1 < t:
            best = order[0]
            with np.errstate(invalid="ignore"):  # inf - inf on dead rows; masked via isfinite above
                live_scores = live_scores - pruned[:, best]
            live_scores[best] = np.inf
    # Coordinate-wise averaged-median over the t selections (cpu.cpp:163-187)
    out = np.empty(d)
    for x in range(d):
        col = selections[:, x]
        med = _nonfinite_last_sorted(col)[t // 2]
        dev = np.abs(col - med)
        dev = np.where(np.isfinite(dev), dev, np.inf)
        closest = col[np.argsort(dev, kind="stable")[:b]]
        out[x] = np.mean(closest)
    return out


def trimmed_mean(grads, f, trim=None):
    """Coordinate-wise b-trimmed mean (extension; see gars/trimmed_mean.py)."""
    grads = np.asarray(grads, dtype=np.float64)
    n, _ = grads.shape
    b = f if trim is None else trim
    clean = np.where(np.isfinite(grads), grads, np.inf)
    ordered = np.sort(clean, axis=0)[b:n - b]
    out = ordered.mean(axis=0)
    return np.where(np.isfinite(out), out, np.nan)


def centered_clip(grads, f, tau=10.0, iters=3):
    """Iterative clipped-deviation center (extension; see gars/centered_clip.py)."""
    grads = np.asarray(grads, dtype=np.float64)
    finite_row = np.all(np.isfinite(grads), axis=-1, keepdims=True)
    safe = np.where(finite_row, grads, 0.0)
    nb_alive = max(float(finite_row.sum()), 1.0)
    masked = np.where(finite_row, grads, np.nan)
    with np.errstate(all="ignore"):
        center = np.nan_to_num(np.nanmedian(masked, axis=0))
    for _ in range(iters):
        deviation = safe - center[None, :]
        norms = np.sqrt((deviation * deviation).sum(axis=-1, keepdims=True))
        scale = np.minimum(1.0, tau / np.maximum(norms, 1e-12))
        center = center + (deviation * scale * finite_row).sum(axis=0) / nb_alive
    return center


def geometric_median(grads, f, iters=8, eps=1e-6):
    """Weiszfeld geometric median (extension; see gars/geometric_median.py)."""
    grads = np.asarray(grads, dtype=np.float64)
    alive = np.all(np.isfinite(grads), axis=-1).astype(np.float64)
    safe = np.where(alive[:, None] > 0, grads, 0.0)
    with np.errstate(all="ignore"):
        z = np.nan_to_num(
            np.nanmedian(np.where(alive[:, None] > 0, grads, np.nan), axis=0)
        )
    for _ in range(iters):
        norms = np.sqrt(((safe - z[None, :]) ** 2).sum(axis=-1))
        weights = alive / np.maximum(norms, eps)
        z = (weights[:, None] * safe).sum(axis=0) / max(float(weights.sum()), 1e-30)
    return z


def bucketing(grads, f, perm, s, inner, **inner_kwargs):
    """Permute, average buckets of s, apply the inner oracle (extension; see
    gars/bucketing.py).  ``perm`` is supplied so tests can mirror the jit
    tier's key-derived permutation."""
    grads = np.asarray(grads, dtype=np.float64)
    n, d = grads.shape
    buckets = grads[np.asarray(perm)].reshape(n // s, s, d).mean(axis=1)
    return inner(buckets, f, **inner_kwargs)


def dnc(grads, f, remove=None, iters=8):
    """Spectral outlier removal (extension; see gars/dnc.py).

    Mirrors the jit tier's ALGORITHM — the same fixed-iteration power method
    on the Gram, not an exact SVD: on a flat spectrum (no attack) the top
    direction is ill-defined and only the matching method gives matching
    selections.  ``remove`` counts LIVE outliers (dead rows are excluded
    outside the budget)."""
    grads = np.asarray(grads, dtype=np.float64)
    n, _ = grads.shape
    remove = f if remove is None else remove
    alive = np.all(np.isfinite(grads), axis=-1)
    safe = np.where(alive[:, None], grads, 0.0)
    nb_alive = max(float(alive.sum()), 1.0)
    mean = safe.sum(axis=0) / nb_alive  # safe is already zero-filled
    centered = (safe - mean[None, :]) * alive[:, None]
    gram = centered @ centered.T
    # diag init, mirroring the jit tier (ones is exactly in K's null space)
    u = np.diagonal(gram).copy()
    u = u / max(np.linalg.norm(u), 1e-30)
    for _ in range(iters):
        u = gram @ u
        u = u / max(np.linalg.norm(u), 1e-30)
    lam = u @ (gram @ u)
    scores = np.where(alive, lam * u * u, np.inf)
    kept_idx = np.argsort(scores, kind="stable")[: max(int(alive.sum()) - remove, 0)]
    kept = np.zeros(n, dtype=bool)
    kept[kept_idx] = True
    kept &= alive
    if not kept.any():
        return np.zeros(grads.shape[1])
    return safe[kept].mean(axis=0)

"""Gradient Aggregation Rules (GARs) — the heart of the framework.

A GAR reduces the ``(n, d)`` matrix of per-worker flattened gradients to one
``(d,)`` aggregated gradient while tolerating up to ``f`` Byzantine rows
(reference: aggregators/__init__.py:40-60).  The reference ships three
implementation tiers per rule (numpy/py_func, pure-TF, C++ custom op); here
the tiers are:

- **jnp** (this package): jit-compiled XLA, the default on-device tier —
  replaces both the pure-TF tier and the C++ CPU/GPU custom ops;
- **oracle** (``gars/oracle.py``): plain numpy, reference-faithful semantics,
  the cross-check used by the property tests (SURVEY.md §4);
- **pallas** (``ops/``): hand-written TPU kernels for the O(n²·d) hot path;
- **native** (``ops/native``): C++ host library via ctypes, parity with the
  reference's ``aggregators/deprecated_native`` tier.

TPU-first design note: every distance-based rule is factored into
``selection_weights(dist2) -> W`` (tiny, O(n²) work, replicated) and a
``W @ block`` combine (MXU matmul, works on *dimension-sharded* column blocks
of the gradient matrix).  The distributed engine in ``parallel/`` exploits
this: the (n, d) matrix never materializes on one device — blocks stay
sharded, only the (n, n) distance matrix is psum-reduced.
"""

from ..utils import ClassRegister, import_directory

gars = ClassRegister("GAR")

#: reserved fold_in tag both engines use to derive the per-step GAR key from
#: the step key — far above any per-worker stream index, so the randomized
#: meta-rules' permutations never collide with the attack/lossy streams
GAR_KEY_TAG = 0x6AC0BEA7


def register(name, cls):
    return gars.register(name, cls)


def itemize():
    return gars.itemize()


def _split_args(text):
    """Split ``k=v,k=v`` on top-level commas only — a parenthesized value
    (a nested rule spec like ``hier(g=4,outer=krum)``) keeps its commas."""
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return [p for p in (p.strip() for p in parts) if p]


def parse_spec(spec):
    """Parse an inline GAR spec into ``(name, [key:value, ...])``.

    Three forms (all equivalent)::

        krum
        hier:g=16,inner=median,outer=krum
        hier(g=16,inner=median,outer=krum)

    Nested composite rules spell their sub-arguments in the parenthesized
    form so the commas stay attached to the inner spec::

        bucketing:s=2,inner=hier(g=8,outer=krum)

    The returned args use the ``key:value`` convention ``parse_keyval``
    expects.  A plain registered name passes through untouched.
    """
    from ..utils import UserException

    spec = str(spec).strip()
    ci, pi = spec.find(":"), spec.find("(")
    if pi != -1 and spec.endswith(")") and (ci == -1 or pi < ci):
        name, _, body = spec.partition("(")
        body = body[:-1]
    elif ci != -1:
        name, _, body = spec.partition(":")
    else:
        return spec, []
    name = name.strip()
    args = []
    for item in _split_args(body):
        if "=" not in item:
            raise UserException(
                "GAR spec argument %r wants key=value (in spec %r)" % (item, spec)
            )
        key, _, value = item.partition("=")
        args.append("%s:%s" % (key.strip(), value.strip()))
    return name, args


def instantiate(name, nb_workers, nb_byz_workers, args=None):
    """Build the GAR registered under ``name`` (reference: aggregators/__init__.py:66-70).

    ``args`` is a list of ``key:value`` strings, the same sub-argument
    convention every other registry uses (attacks, optimizers, experiments).
    ``name`` may also be an inline spec (``hier:g=16,outer=krum`` — see
    :func:`parse_spec`); spec args and explicit ``args`` concatenate, with
    duplicate keys rejected by ``parse_keyval``.
    """
    name, spec_args = parse_spec(name)
    return gars.get(name)(nb_workers, nb_byz_workers, spec_args + list(args or []))


class GAR:
    """Base Gradient Aggregation Rule.

    Subclasses implement ``aggregate_block``; ``aggregate`` is the dense
    convenience entry that computes the distance matrix when needed.

    Attributes:
      coordinate_wise: True if the rule treats coordinates independently, so a
        column block can be aggregated with no cross-block information.
      needs_distances: True if ``aggregate_block`` requires the global (n, n)
        pairwise squared-distance matrix (Krum/Bulyan family).
    """

    coordinate_wise = False
    needs_distances = False
    #: True if ``aggregate_block`` accepts ``axis_name=`` for cross-block
    #: reductions (iterative rules needing global row norms: the engine
    #: passes the worker mesh axis so blockwise results match the dense tier
    #: exactly, at one O(n) psum per internal iteration)
    uses_axis = False
    #: True if ``aggregate_block`` accepts ``key=`` (a replicated per-step
    #: PRNG key) — randomized meta-rules (bucketing) re-draw their
    #: permutation every step; the key is identical on every device and
    #: block, so the randomness never breaks replication
    uses_key = False
    #: True if an all-NaN row is cleanly EXCLUDED from the aggregate (never
    #: selected / weight 0) rather than poisoning it — the property the
    #: lossy link's NaN infill and the reputation quarantine rely on
    nan_row_tolerant = False
    #: typed key:value argument defaults accepted by this rule (strict: an
    #: unknown key raises instead of being silently ignored)
    ARG_DEFAULTS = {}

    def __init__(self, nb_workers, nb_byz_workers, args=None):
        from ..utils import parse_keyval

        self.nb_workers = int(nb_workers)
        self.nb_byz_workers = int(nb_byz_workers)
        self.args = parse_keyval(args, self.ARG_DEFAULTS, strict=True)
        self.check()

    def check(self):
        """Validate the (n, f) relation; raise UserException when unsatisfiable."""
        from ..utils import UserException

        if self.nb_workers < 1:
            raise UserException("GAR %r needs at least 1 worker" % type(self).__name__)
        if self.nb_byz_workers < 0:
            raise UserException("Negative declared Byzantine count")
        # Universal feasibility floor (graftcheck GC002): NO rule can
        # tolerate a Byzantine majority of everyone — f >= n leaves zero
        # honest rows to aggregate, and every declared-f budget downstream
        # (NaN infill, bounded-wait timeouts, forgery rejection, guardian
        # f+K re-sizing) silently overdraws.  Rejected here, at parse time,
        # for every rule — per-rule checks only tighten this further.
        if self.nb_byz_workers >= self.nb_workers:
            raise UserException(
                "GAR %r cannot declare f=%d >= n=%d: at least one worker "
                "must be honest for any aggregate to mean anything"
                % (type(self).__name__, self.nb_byz_workers, self.nb_workers)
            )

    def aggregate(self, grads, key=None):
        """Dense tier: reduce the full (n, d) matrix to (d,)."""
        from .common import pairwise_sq_distances

        dist2 = pairwise_sq_distances(grads) if self.needs_distances else None
        return self._call_aggregate(grads, dist2, axis_name=None, key=key)

    def _drop_memos(self):
        """Drop ``memo_by_identity`` entries created during this pass: they
        hold (tracer-arg, tracer-result) tuples that must not outlive the
        outer call (gars/common.py memo docstring)."""
        for name in [a for a in vars(self) if a.startswith("_memo_")]:
            delattr(self, name)

    def _call_aggregate(self, block, dist2, axis_name=None, key=None, keep_memo=False):
        """Invoke ``aggregate_block`` with exactly the keywords this rule
        declares (``uses_axis``/``uses_key``) — the single dispatch point the
        engines use, so plain rules keep their two-argument signature.

        Memo entries are dropped on exit (they hold tracers, see
        ``_drop_memos``) unless ``keep_memo`` — the one caller that needs
        the memo to survive is ``aggregate_block_and_participation``, whose
        participation read reuses the selection graph and which drops the
        memo itself afterwards."""
        kwargs = {}
        if self.uses_axis:
            kwargs["axis_name"] = axis_name
        if self.uses_key:
            kwargs["key"] = key
        try:
            return self.aggregate_block(block, dist2, **kwargs)
        finally:
            if not keep_memo:
                self._drop_memos()

    def aggregate_block(self, block, dist2=None):
        """Blockwise tier: reduce an (n, d_block) column block to (d_block,).

        ``dist2`` is the *global* (n, n) squared-distance matrix (already
        reduced across blocks) when ``needs_distances`` is set.
        """
        raise NotImplementedError

    def worker_participation(self, dist2):
        """Optional (n,) diagnostic: how much weight each worker's gradient
        carried in the aggregate (sums to 1).  Selection-based rules override
        this — a worker the rule consistently excludes is a suspect, the
        observable the Byzantine-ML literature uses to *detect* attackers
        rather than only absorb them.  None = not defined for this rule
        (coordinate-wise rules select per coordinate, not per worker)."""
        return None

    def aggregate_block_and_participation(self, block, dist2=None, axis_name=None, key=None):
        """Aggregate a block AND return the (n,) participation (or None).

        One entry point so iterative rules (geometric-median) can expose the
        weights their own iteration already computes — in one pass, with no
        state stashed on the instance between calls (a stashed jnp value
        would be a tracer leaking across trace boundaries)."""
        try:
            agg = self._call_aggregate(
                block, dist2, axis_name=axis_name, key=key, keep_memo=True
            )
            return agg, self.worker_participation(dist2)
        finally:
            self._drop_memos()


# Self-registering rule modules (reference: aggregators/__init__.py:76-85)
import_directory(__name__, __path__, skip=("oracle",))

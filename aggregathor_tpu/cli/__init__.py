"""Command-line entry points: ``runner`` (training) and ``deploy`` (multi-host).

Mirrors the reference's L7 deployment layer (deploy.py, runner.py) with an
argument-compatible surface re-based on the SPMD engine: there is no cluster
of tf.train.Servers to stand up — ``runner`` drives the whole synchronous
robust-SGD program on the local mesh, and ``deploy`` initializes JAX's
multi-process runtime so the same program spans hosts over ICI/DCN.
"""


def console_entry(main):
    """Run a CLI main: UserException -> clean error + exit code 1 (reference: tools/__init__.py:232-258)."""
    from ..utils import UserException, error

    try:
        return main()
    except UserException as exc:
        error(str(exc))
        return 1

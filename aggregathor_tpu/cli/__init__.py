"""Command-line entry points: ``runner`` (training) and ``deploy`` (multi-host).

Mirrors the reference's L7 deployment layer (deploy.py, runner.py) with an
argument-compatible surface re-based on the SPMD engine: there is no cluster
of tf.train.Servers to stand up — ``runner`` drives the whole synchronous
robust-SGD program on the local mesh, and ``deploy`` initializes JAX's
multi-process runtime so the same program spans hosts over ICI/DCN.
"""


def console_entry(main):
    """Run a CLI main: UserException -> clean error + exit code 1 (reference: tools/__init__.py:232-258)."""
    from ..utils import UserException, error

    try:
        return main()
    except UserException as exc:
        error(str(exc))
        return 1


def add_causal_flags(parser):
    """The causal-plane flags every journaling CLI shares
    (docs/observability.md "The causal plane"): ``--cause`` makes this
    process's ``run_start`` cite the journal event that spawned it (the
    supervisor injects the token on action respawns — supervisor/actuator),
    ``--journal-max-bytes`` bounds one journal file via segment rotation
    (obs/events.py ``Journal(max_bytes=...)``)."""
    parser.add_argument("--cause", default=None, metavar="INSTANCE:RUN_ID:SEQ",
                        help="cause reference stamped on this run's run_start "
                             "event: the journal event that spawned this "
                             "process (cli.postmortem replays the chain)")
    parser.add_argument("--journal-max-bytes", type=int, default=None,
                        metavar="N",
                        help="rotate the journal after the write crossing N "
                             "bytes; rolled segments become PATH.1, PATH.2, "
                             "... (default: never rotate)")
    return parser


def parse_cause_flag(token):
    """``--cause`` token -> cause reference dict (or None).  A garbled
    token fails the LAUNCH (UserException), never the journal — an
    operator typo must be loud, not a dangling reference."""
    from ..obs import events as obs_events
    from ..utils import UserException

    if token is None:
        return None
    try:
        return obs_events.parse_cause(token)
    except ValueError as exc:
        raise UserException("--cause: %s" % (exc,))

"""Fleet postmortem runner: replay N journals as one verified story.

The causal plane's operator door (obs/causal.py, docs/observability.md
"The causal plane"): point it at every journal a run left behind —
trainer, serve replicas, router, supervisor — and it merges them into one
causally ordered timeline, audits the cause-reference DAG (dangling
edges, orphan actuations, unanswered spawn chains, rollbacks that fail to
name their sentinel verdict) and writes the
``aggregathor.obs.postmortem.v1`` report plus a markdown story.

**The exit code IS the verdict**: 0 when every chain closes and every
reference resolves, 1 when the journals cannot carry the story they
claim (including a journal that fails to load — a truncated file is
destroyed evidence, not a smaller story).  CI gates on it
(scripts/run_postmortem_smoke.sh, benchmarks/causal_audit.py).

Example::

  python -m aggregathor_tpu.cli.postmortem \
      --journal train=out/train.jsonl --journal router=out/router.jsonl \
      --journal supervisor=out/supervisor.jsonl \
      --report out/postmortem.json --story out/postmortem.md
"""

import argparse
import json
import sys


def build_parser():
    parser = argparse.ArgumentParser(
        prog="aggregathor-tpu postmortem",
        description="merge + audit fleet journals into one verified story "
                    "(exit code 0 = every causal chain closes)",
    )
    parser.add_argument("--journal", action="append", default=[],
                        required=True, metavar="NAME=PATH",
                        help="one instance's journal (repeatable); NAME must "
                             "match the instance name cause references use "
                             "(the supervisor's --instance-name, the "
                             "router's instance_name)")
    parser.add_argument("--report", default=None, metavar="JSON",
                        help="write the aggregathor.obs.postmortem.v1 report "
                             "here (default: stdout)")
    parser.add_argument("--story", default=None, metavar="MD",
                        help="write the markdown story here")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress the stdout report when --report is "
                             "given")
    return parser


def parse_sources(specs):
    from ..utils import UserException

    sources = {}
    for spec in specs:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            raise UserException("--journal %r: expected NAME=PATH" % spec)
        if name in sources:
            raise UserException("--journal: name %r given twice" % name)
        sources[name] = path
    return sources


def main(argv=None):
    args = build_parser().parse_args(argv)

    from ..obs import causal
    from ..utils import info, warning

    sources = parse_sources(args.journal)
    report = causal.run_postmortem(sources,
                                   include_timeline=bool(args.story))
    timeline = report.pop("timeline", None)
    body = json.dumps(report, indent=2, sort_keys=True)
    if args.report:
        with open(args.report, "w") as fd:
            fd.write(body + "\n")
        info("Postmortem report -> %r" % (args.report,))
    if args.report is None or not args.quiet:
        print(body)
    if args.story:
        with open(args.story, "w") as fd:
            fd.write(causal.render_story(report, timeline))
        info("Postmortem story -> %r" % (args.story,))
    if report["verdict"] != "PASS":
        warning("Postmortem verdict: FAIL (%s)"
                % ", ".join(report["failing"]))
        return 1
    info("Postmortem verdict: PASS (%d event(s), %d edge(s), %d chain(s))"
         % (report["events_total"], report["edges_total"],
            len(report["chains"])))
    return 0


def cli():
    from . import console_entry

    return console_entry(main)


if __name__ == "__main__":
    sys.exit(cli())

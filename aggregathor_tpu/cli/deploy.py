"""Multi-host deployment: the reference's ``deploy.py`` re-based on JAX.

The reference bootstraps a TF server per node over SSH/mpirun and wires a
ClusterSpec of ps/worker/eval jobs (reference: deploy.py:190-309).  A JAX
multi-host program needs none of that choreography: every host runs the SAME
single-controller SPMD program; ``jax.distributed.initialize`` connects the
hosts (coordinator + process ranks) and the global device mesh spans all of
them over ICI/DCN.  This shim does exactly that and then hands over to the
runner — deployment collapses from 329 lines of SSH plumbing to "initialize,
then run".

Usage, one invocation per host (what SLURM/GKE/`gcloud compute tpus ssh
--worker=all` would issue)::

  python3 -m aggregathor_tpu.cli.deploy \
      --coordinator-address HOST0:1234 --num-processes 4 --process-id $RANK \
      -- --experiment mnist --aggregator krum --nb-workers 32 ...

On Cloud TPU the three flags can be omitted entirely
(``jax.distributed.initialize`` auto-detects the pod topology from the TPU
metadata); arguments after ``--`` go to the runner verbatim.

``--local-simulate K`` instead forks K local processes that form a K-process
CPU "cluster" on localhost — the single-machine deployment story of the
reference (README.md:141-146) and the integration-test hook for the DCN path.

``--cluster SPEC`` resolves the three flags from the reference's cluster-spec
forms (inline JSON / file / ``G5k`` reading ``$OAR_FILE_NODES`` —
tools/cluster.py:48-91) via ``utils.cluster.cluster_spec``.
"""

import argparse
import os
import subprocess
import sys


def build_parser():
    parser = argparse.ArgumentParser(
        prog="aggregathor-tpu deploy", description="Multi-host bring-up for the runner"
    )
    parser.add_argument("--coordinator-address", default=None, help="host:port of process 0")
    parser.add_argument("--num-processes", type=int, default=None, help="total process count")
    parser.add_argument("--process-id", type=int, default=None, help="this process' rank")
    parser.add_argument(
        "--cluster", default=None, metavar="SPEC",
        help="resolve the bring-up triple from a cluster spec instead of the "
             "three flags above: inline JSON ('[\"h0\",\"h1\"]' or "
             "'{\"hosts\": [...], \"port\": N}'), a nodefile/JSON path, or "
             "'G5k' to read $OAR_FILE_NODES — the reference's --cluster "
             "forms (tools/cluster.py:48-91) mapped to SPMD bring-up; this "
             "host's rank comes from hostname match or $AGGREGATHOR_PROCESS_ID",
    )
    parser.add_argument(
        "--local-simulate", type=int, default=0, metavar="K",
        help="fork K local CPU processes forming a cluster on localhost (single-machine parity)",
    )
    parser.add_argument("--devices-per-process", type=int, default=1,
                        help="(--local-simulate only) virtual CPU devices "
                             "per forked process, so a K-process x D-device "
                             "cluster — the reference's multi-node multi-GPU "
                             "shape (deploy.py:244-309) — is testable on one "
                             "machine")
    parser.add_argument("--port", type=int, default=None,
                        help="coordinator port when the spec names none (default 7000, "
                             "the reference's fixed port, tools/cluster.py:60)")
    parser.add_argument("runner_args", nargs=argparse.REMAINDER, help="arguments after -- go to the runner")
    return parser


def _strip_separator(rest):
    return rest[1:] if rest and rest[0] == "--" else rest


def local_simulate(nb_processes, port, runner_args, devices_per_process=1):
    """Fork a K-process localhost cluster (CPU devices) running the runner."""
    procs = []
    for rank in range(nb_processes):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # default: the cluster IS the mesh
        if devices_per_process > 1:
            env["XLA_FLAGS"] = (
                "--xla_force_host_platform_device_count=%d" % devices_per_process)
        cmd = [
            sys.executable, "-m", "aggregathor_tpu.cli.deploy",
            "--coordinator-address", "127.0.0.1:%d" % port,
            "--num-processes", str(nb_processes),
            "--process-id", str(rank),
            "--",
        ] + runner_args
        procs.append(subprocess.Popen(cmd, env=env))
    code = 0
    for proc in procs:
        code = proc.wait() or code
    return code


def main(argv=None):
    args = build_parser().parse_args(argv)
    runner_args = _strip_separator(args.runner_args)
    if args.devices_per_process != 1 and args.local_simulate <= 0:
        from ..utils import UserException

        raise UserException(
            "--devices-per-process shapes the forked --local-simulate "
            "cluster only; for a real cluster set XLA_FLAGS="
            "--xla_force_host_platform_device_count (or run on real chips) "
            "in each process' environment"
        )
    if args.local_simulate > 0:
        from ..utils.cluster import DEFAULT_PORT

        return local_simulate(args.local_simulate, args.port or DEFAULT_PORT,
                              runner_args, args.devices_per_process)
    if args.cluster is not None:
        if (
            args.coordinator_address is not None
            or args.num_processes is not None
            or args.process_id is not None
        ):
            from ..utils import UserException

            raise UserException(
                "--cluster and --coordinator-address/--num-processes/"
                "--process-id are two ways to name the same thing; pass one "
                "(a spec'd host's rank can be pinned via "
                "$AGGREGATHOR_PROCESS_ID)"
            )
        from ..utils.cluster import cluster_spec

        (args.coordinator_address, args.num_processes, args.process_id) = cluster_spec(
            args.cluster, port=args.port
        )

    import jax

    platform = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if platform:
        # The env var alone can be overridden by an ambient accelerator
        # plugin, sending jax.distributed.initialize into that plugin's
        # coordination bootstrap (which can hang); the config-level pin wins
        # as long as no backend has been initialized yet (cli/runner.py does
        # the same dance).
        jax.config.update("jax_platforms", platform)

    kwargs = {}
    if args.coordinator_address is not None:
        kwargs = {
            "coordinator_address": args.coordinator_address,
            "num_processes": args.num_processes,
            "process_id": args.process_id,
        }
    jax.distributed.initialize(**kwargs)

    from . import runner

    return runner.main(runner_args)


def cli():
    from . import console_entry

    return console_entry(main)


if __name__ == "__main__":
    sys.exit(cli())

"""Fleet supervisor runner: the self-driving run (docs/operations.md).

Point it at a fleet spec (JSON: the instances' argvs, scrape URLs,
journals, sentinel verdict files, checkpoint directories and retune
ladders) and it spawns the fleet and closes the control loop the control
room opened: every tick it scrapes health (obs/fleet.py), tails the
instances' causal journals (incremental cursors — obs/events.py
``tail_journal``) and reads fresh sentinel verdicts (obs/slo.py), feeds
them to the pure :class:`~aggregathor_tpu.supervisor.SupervisorPolicy`,
and executes the returned actions: restart dead/hung instances under
exponential backoff, quarantine crash-loopers, retune knobs through an
argv rebuild + graceful restart, roll checkpoint timelines back through
the custody path on REGRESS — every action a typed
``supervisor_*`` journal event with its triggering evidence.

Example::

  python -m aggregathor_tpu.cli.supervise \
      --fleet out/fleet.json --journal out/supervisor.jsonl \
      --tick-interval 0.5 --supervisor-args patience:3 max-restarts:4
"""

import argparse
import os
import signal
import sys
import threading


def build_parser():
    parser = argparse.ArgumentParser(
        prog="aggregathor-tpu supervise",
        description="fleet supervisor: restart, retune and roll back a "
                    "train+serve+router fleet with zero human action",
    )
    parser.add_argument("--fleet", required=True, metavar="JSON",
                        help="fleet spec file: {\"instances\": [{name, role, "
                             "argv, url/ready_file, journal, verdict, "
                             "checkpoint_dir, retunes, ...}, ...]}")
    parser.add_argument("--tick-interval", type=float, default=1.0, metavar="S",
                        help="seconds between sense->decide->act rounds")
    parser.add_argument("--down-after", type=int, default=3, metavar="N",
                        help="consecutive scrape misses before an instance "
                             "reads down (the restart trigger for hangs)")
    parser.add_argument("--scrape-timeout", type=float, default=2.0, metavar="S",
                        help="per-instance scrape fetch timeout")
    parser.add_argument("--supervisor-args", nargs="*", default=[],
                        metavar="KEY:VALUE",
                        help="policy knobs: patience, backoff, max-restarts, "
                             "flap-window, retune-streak, retune-cooldown "
                             "(supervisor/policy.py)")
    parser.add_argument("--max-ticks", type=int, default=None, metavar="N",
                        help="exit after N ticks (smokes; default: run until "
                             "SIGTERM/SIGINT)")
    parser.add_argument("--ready-file", default=None, metavar="PATH",
                        help="write 'host port pid' (host/port are 0: the "
                             "supervisor serves nothing) once the fleet is "
                             "spawned (harness handshake)")
    parser.add_argument("--journal", default=None, metavar="JSONL",
                        help="the supervisor's own causal journal: every "
                             "supervisor_* action with its evidence")
    parser.add_argument("--run-id", default=None, metavar="ID",
                        help="run id stamped on journal lines (default: "
                             "generated)")
    parser.add_argument("--keep-fleet", action="store_true",
                        help="leave the fleet running on exit (default: "
                             "SIGTERM every instance the supervisor spawned)")
    parser.add_argument("--instance-name", default="supervisor", metavar="NAME",
                        help="this supervisor's name in cross-journal cause "
                             "references (children spawned by an action cite "
                             "NAME:RUN_ID:SEQ; may not contain ':')")
    from . import add_causal_flags

    add_causal_flags(parser)
    return parser


def main(argv=None):
    args = build_parser().parse_args(argv)

    from ..obs import events as obs_events
    from ..obs.summaries import make_run_id
    from ..supervisor import FleetSupervisor, SupervisorConfig
    from ..supervisor.actuator import load_fleet_spec
    from ..utils import info

    from . import parse_cause_flag

    specs = load_fleet_spec(args.fleet)
    config = SupervisorConfig(args.supervisor_args)
    run_id = args.run_id if args.run_id else make_run_id()
    cause = parse_cause_flag(args.cause)
    if args.journal:
        obs_events.install(args.journal, run_id=run_id,
                           max_bytes=args.journal_max_bytes)
        obs_events.emit("run_start", role="supervisor",
                        instances=sorted(s.name for s in specs),
                        config=config.describe(), pid=os.getpid(),
                        cause=cause)
        info("Run journal to %r (run_id %s)" % (args.journal, run_id))

    supervisor = FleetSupervisor(
        specs, config=config, down_after=args.down_after,
        scrape_timeout=args.scrape_timeout,
        instance_name=args.instance_name,
    )

    stop = threading.Event()

    def on_signal(signum, frame):
        info("Signal %d: supervisor shutting down" % signum)
        stop.set()

    previous = {
        signal.SIGINT: signal.signal(signal.SIGINT, on_signal),
        signal.SIGTERM: signal.signal(signal.SIGTERM, on_signal),
    }
    try:
        supervisor.start()
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as fd:
                fd.write("0 0 %d\n" % os.getpid())
            os.replace(tmp, args.ready_file)  # atomic: never a torn line
        info("Supervising %d instance(s): %s (%s)"
             % (len(specs), ", ".join(sorted(s.name for s in specs)),
                config.describe()))
        ticks = supervisor.run(
            tick_interval=args.tick_interval,
            should_stop=stop.is_set,
            max_ticks=args.max_ticks,
        )
        info("Supervisor loop ended after %d tick(s)" % ticks)
    finally:
        for signum, handler in previous.items():
            signal.signal(signum, handler)
        if not args.keep_fleet:
            supervisor.stop()
        if args.journal and obs_events.installed() is not None:
            obs_events.emit("run_end", role="supervisor")
            written = obs_events.uninstall()
            info("Run journal -> %r (run_id %s)" % (written, run_id))
    return 0


def cli():
    from . import console_entry

    return console_entry(main)


if __name__ == "__main__":
    sys.exit(cli())

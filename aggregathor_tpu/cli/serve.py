"""Serving runner: checkpoint -> Byzantine-robust HTTP inference (serve/ v2).

The serving sibling of ``cli/runner.py``: loads a trained checkpoint
(``obs/checkpoint.py`` restore — the authenticator and at-rest cipher are
honored via the same ``--session-secret`` flags training uses), builds an
R-way replicated :class:`serve.engine.InferenceEngine` with a GAR vote over
replica logits, and serves ``/predict`` / ``/healthz`` / ``/metrics`` /
``/status`` through the v2 stack (docs/serving.md): the asyncio front end
(``serve/frontend.py``), continuous batching on the bucket ladder
(``serve/continuous.py``, ``--lanes``/``--max-lanes``/``--linger-ms``),
optional registry-driven autoscaling (``--autoscale``,
``serve/autoscale.py``) and the zero-downtime weight pipeline
(``--follow``, ``serve/weights.py``).

Replica sources:

- one ``--ckpt-dir`` + ``--replicas R``: R copies of the latest snapshot
  (identical replicas — the vote then masks injected faults exactly);
- several ``--ckpt-dir`` paths: one replica per directory (distinct
  checkpoints, e.g. staggered training steps or fine-tunes).

``--poison-replica INDEX:MODE[=VALUE]`` (repeatable) injects the chaos
replica-fault modes (``chaos/replica_faults.py``: nan / scale / zero /
noise / stale) — the fault-injection hook the smoke script, the serve
campaign and the load benchmark drive to prove the vote masks a corrupted
replica in production configuration, not just in unit tests.  Poison specs
are RE-APPLIED on every hot swap: a poisoned test replica stays poisoned
across the weight pipeline, which is what lets ``benchmarks/serve_load.py``
drive mid-run swaps against a faulty pool.

Chain of custody (docs/security.md): with ``--session-secret``, every
restored checkpoint's signed lineage manifest (written by ``--secure``
training) is verified before loading — an unsigned checkpoint is refused
unless ``--allow-unsigned`` — and ``/healthz`` reports
``custody_verified``.  Hot swaps re-verify through the SAME custody path:
``--follow`` polls the snapshot directory and swaps newer steps in with
zero recompiles and zero dropped requests; ``SIGHUP`` forces one reload
now (requests keep flowing; a bad snapshot keeps the previous weights).

Signals: ``SIGTERM`` drains — ``/status`` flips ``draining`` so the fleet
router (``cli/router.py``) re-routes NEW traffic while in-flight requests
finish; the process exits at quiescence or after ``--drain-timeout``
(journaled as ``serve_drain``).  ``SIGINT`` stops immediately.

The ``--ready-file`` handshake fires only after the bucket-ladder warmup
compiles finish AND the front end is bound — a reader of the ready file
never races a cold bucket with its first request.

Example::

  python -m aggregathor_tpu.cli.serve --experiment digits \
      --ckpt-dir out/ckpt --replicas 3 --gar median \
      --port 8000 --max-batch 64 --lanes 2 --max-lanes 4 --autoscale \
      --follow
"""

import argparse
import os
import signal
import sys
import threading
import time


def build_parser():
    parser = argparse.ArgumentParser(
        prog="aggregathor-tpu serve",
        description="Byzantine-robust batched inference serving",
    )
    parser.add_argument("--experiment", required=True, help="experiment name (models registry)")
    parser.add_argument("--experiment-args", nargs="*", default=[], help="key:value experiment arguments")
    parser.add_argument("--ckpt-dir", nargs="+", required=True, metavar="DIR",
                        help="checkpoint directory (one: replicated --replicas times; "
                             "several: one replica each)")
    parser.add_argument("--ckpt-step", type=int, default=None,
                        help="serve this snapshot step (default: latest per directory)")
    parser.add_argument("--checkpoint-base-name", default=None, help="checkpoint file base name")
    parser.add_argument("--replicas", type=int, default=None,
                        help="replica count R (default: number of --ckpt-dir paths)")
    parser.add_argument("--gar", default="median",
                        help="vote rule over replica logits (gars registry; 'none' disables "
                             "the vote and serves replica 0)")
    parser.add_argument("--gar-args", nargs="*", default=[], help="key:value vote-rule arguments")
    parser.add_argument("--replica-byz", type=int, default=None, metavar="F",
                        help="declared faulty-replica budget f for the vote rule "
                             "(default (R-1)//2)")
    parser.add_argument("--poison-replica", action="append", default=[], metavar="IDX:MODE[=V]",
                        help="chaos tie-in: corrupt replica IDX with a replica fault "
                             "(nan|scale=X|zero|noise=S|stale); repeatable; re-applied "
                             "on every hot swap")
    # Restore template: must match the optimizer the snapshot was trained with
    parser.add_argument("--optimizer", default="sgd", help="optimizer the checkpoint was trained with")
    parser.add_argument("--optimizer-args", nargs="*", default=[], help="key:value optimizer arguments")
    parser.add_argument("--session-secret", default=None, metavar="SECRET",
                        help="verify checkpoint HMAC tags under this secret (training's "
                             "--session-secret; restore fails on tampered snapshots)")
    parser.add_argument("--no-legacy-checkpoint-tags", action="store_true",
                        help="refuse snapshots tagged under the legacy key scheme")
    parser.add_argument("--encrypt-checkpoints", action="store_true",
                        help="snapshots are encrypted at rest (requires --session-secret)")
    parser.add_argument("--allow-unsigned", action="store_true",
                        help="serve checkpoints WITHOUT a custody manifest: with "
                             "--session-secret the chain-of-custody manifest "
                             "(written by --secure training) is verified before "
                             "loading and an unsigned checkpoint is REFUSED "
                             "unless this explicit opt-out is passed "
                             "(/healthz then reports custody_verified false)")
    # Scheduling / shedding (serve/continuous.py)
    parser.add_argument("--max-batch", type=int, default=64, help="bucket ladder top / batch cap")
    parser.add_argument("--buckets", default=None, metavar="B1,B2,...",
                        help="explicit bucket ladder (default: powers of two up to --max-batch)")
    parser.add_argument("--lanes", type=int, default=1,
                        help="initial dispatch lanes (concurrent in-flight batches over "
                             "the one compiled ladder)")
    parser.add_argument("--max-lanes", type=int, default=None,
                        help="lane ceiling the autoscaler may climb to (default --lanes)")
    parser.add_argument("--linger-ms", type=float, default=0.0,
                        help="optional sub-top coalescing window; 0 = pure continuous "
                             "batching (dispatch the instant a lane frees)")
    parser.add_argument("--queue-bound", type=int, default=256,
                        help="queued-row bound beyond which requests are shed (HTTP 429)")
    parser.add_argument("--flag-threshold", type=float, default=None,
                        help="flag a replica suspect when its disagreement exceeds this "
                             "(non-finite always flags)")
    parser.add_argument("--no-warmup", action="store_true",
                        help="skip compiling the bucket ladder up front (first requests "
                             "then pay the compiles)")
    # Autoscaling (serve/autoscale.py)
    parser.add_argument("--autoscale", action="store_true",
                        help="scale lanes (and, under sustained pressure, the vote pool "
                             "within the declared-f floor) from the live registry")
    parser.add_argument("--autoscale-args", nargs="*", default=[], metavar="K:V",
                        help="autoscale knobs (serve/autoscale.py AutoscaleConfig: "
                             "interval, high-queue, low-queue, high-p99, low-p99, "
                             "high-shed, low-shed, up-patience, down-patience, "
                             "cooldown, fault-reserve, min-lanes)")
    # Weight pipeline (serve/weights.py)
    parser.add_argument("--follow", action="store_true",
                        help="follow the checkpoint director(ies): poll for newer "
                             "snapshots and hot-swap them in (custody re-verified, "
                             "zero recompiles, zero dropped requests)")
    parser.add_argument("--follow-interval", type=float, default=2.0, metavar="S",
                        help="snapshot poll period in seconds for --follow")
    # HTTP / observability
    parser.add_argument("--host", default="127.0.0.1", help="bind address")
    parser.add_argument("--port", type=int, default=8000, help="bind port (0 = ephemeral)")
    parser.add_argument("--ready-file", default=None, metavar="PATH",
                        help="write 'host port pid' here once the warmup compiles are "
                             "done AND the front end is bound (harness handshake)")
    parser.add_argument("--summary-dir", default=None,
                        help="JSONL serve_batch/serve_shed/serve_autoscale/"
                             "serve_weight_swap event directory (obs/summaries)")
    parser.add_argument("--trace-file", default=None, metavar="PATH",
                        help="write a Chrome trace-event JSON of the request "
                             "lifecycle spans (enqueue -> batch -> jit -> reply) "
                             "here at shutdown — Perfetto-loadable (obs/trace)")
    parser.add_argument("--journal", default=None, metavar="JSONL",
                        help="causal run journal (obs/events.py): append every "
                             "serving decision — autoscale moves, weight swaps "
                             "and their failures — as typed JSONL (schema "
                             "aggregathor.obs.events.v2); merged fleet-wide by "
                             "obs/fleet.py /fleet/journal")
    parser.add_argument("--run-id", default=None, metavar="ID",
                        help="run id stamped on summary lines and trace metadata "
                             "(default: generated)")
    parser.add_argument("--request-timeout", type=float, default=60.0,
                        help="seconds a /predict handler waits on its batch")
    parser.add_argument("--drain-timeout", type=float, default=30.0, metavar="S",
                        help="SIGTERM drain bound: seconds to wait for in-flight "
                             "requests to finish (the fleet router re-routes new "
                             "traffic off a draining /status) before exiting anyway")
    parser.add_argument("--seed", type=int, default=0, help="base PRNG seed (template init)")
    parser.add_argument("--platform", default=None, help="force a JAX platform (tpu/cpu)")
    from . import add_causal_flags

    add_causal_flags(parser)
    return parser


def load_replicas(args, experiment, step=None):
    """Resolve the replica parameter sets: checkpoint restores + poison specs.

    Returns ``(replicas, sources, custody_verified, served_step)`` —
    ``sources`` is the human-readable per-replica provenance logged at
    startup and reported by /healthz's operator story ("which checkpoint is
    replica 2, and is it poisoned?"); ``custody_verified`` is the
    chain-of-custody verdict (True = every restored checkpoint's signed
    lineage manifest verified, False = an unsigned restore was allowed
    through ``--allow-unsigned``, None = no ``--session-secret``,
    verification not attempted); ``served_step`` is the step the non-stale
    replicas restored at (None when distinct directories restored at
    different steps — a mixed pool has no one step to tag responses with).
    ``step`` pins the restore (the weight pipeline's reload path, beating
    ``args.ckpt_step``).  Called again on every hot swap, so a fresh
    custody tally is built per load and poison specs are re-applied.
    """
    from .. import config
    from ..chaos.replica_faults import corrupt_params, parse_poison
    from ..core import build_optimizer, build_schedule
    from ..obs import Checkpoints
    from ..serve.engine import restore_params
    from ..utils import UserException

    tx = build_optimizer(
        args.optimizer, build_schedule("fixed", ["initial-rate:0.01"]), args.optimizer_args
    )
    authenticator = None
    cipher = None
    custody = None
    if args.encrypt_checkpoints and not args.session_secret:
        raise UserException("--encrypt-checkpoints derives its key from --session-secret; pass both")
    if args.session_secret:
        from ..parallel.auth import GradientAuthenticator
        from ..secure import ChainOfCustody

        authenticator = GradientAuthenticator(args.session_secret.encode(), 1, context=b"ckpt")
        custody = ChainOfCustody(
            args.session_secret.encode(), allow_unsigned=args.allow_unsigned
        )
        if args.encrypt_checkpoints:
            from ..parallel.crypto import SnapshotCipher

            cipher = SnapshotCipher(args.session_secret.encode())

    def restore(directory, step=None):
        return restore_params(
            experiment, directory, tx, step=step, seed=args.seed,
            base_name=args.checkpoint_base_name,
            authenticator=authenticator, cipher=cipher,
            allow_legacy_tags=not args.no_legacy_checkpoint_tags,
            custody=custody,
        )

    dirs = list(args.ckpt_dir)
    nb_replicas = args.replicas if args.replicas is not None else len(dirs)
    if nb_replicas < 1:
        raise UserException("--replicas must be >= 1")
    if len(dirs) == 1:
        dirs = dirs * nb_replicas
    elif len(dirs) != nb_replicas:
        raise UserException(
            "%d --ckpt-dir paths but --replicas %d: give one directory, or one per replica"
            % (len(dirs), nb_replicas)
        )

    poisons = {}
    for spec in args.poison_replica:
        index, mode, value = parse_poison(spec)
        if index >= nb_replicas:
            raise UserException(
                "--poison-replica %r: replica %d does not exist (R=%d)"
                % (spec, index, nb_replicas)
            )
        if index in poisons:
            raise UserException("--poison-replica: replica %d poisoned twice" % index)
        poisons[index] = (mode, value)

    pinned = step if step is not None else args.ckpt_step
    replicas, sources = [], []
    steps_seen = set()
    cache = {}
    for index, directory in enumerate(dirs):
        poison = poisons.get(index)
        if poison is not None and poison[0] == "stale":
            on_disk = Checkpoints(
                directory,
                args.checkpoint_base_name if args.checkpoint_base_name is not None
                else config.default_checkpoint_base_name,
            ).steps()
            if len(on_disk) < 2:
                raise UserException(
                    "--poison-replica %d:stale needs at least two snapshots in %r"
                    % (index, directory)
                )
            params, at_step = restore(directory, step=on_disk[0])
            sources.append("%s@%d (stale)" % (directory, at_step))
        else:
            key = (directory, pinned)
            if key not in cache:
                cache[key] = restore(directory, step=pinned)
            params, at_step = cache[key]
            steps_seen.add(int(at_step))
            if poison is not None:
                mode, value = poison
                params = corrupt_params(params, mode, value, seed=args.seed + 31 * index)
                sources.append("%s@%d (poisoned: %s)" % (directory, at_step, mode))
            else:
                sources.append("%s@%d" % (directory, at_step))
        replicas.append(params)
    custody_verified = None if custody is None else custody.all_verified
    served_step = steps_seen.pop() if len(steps_seen) == 1 else None
    return replicas, sources, custody_verified, served_step


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from .. import config, gars, models
    from ..obs import Checkpoints, SummaryWriter, trace
    from ..obs.summaries import make_run_id
    from ..serve import (
        AutoscaleConfig,
        CheckpointWatcher,
        InferenceEngine,
        InferenceServer,
        PoolAutoscaler,
    )
    from ..utils import Context, UserException, info

    run_id = args.run_id if args.run_id else make_run_id()
    if args.trace_file:
        # installed BEFORE compile so the warmup's serve.jit spans land too
        trace.install(args.trace_file, run_id=run_id)
    if args.journal:
        from . import parse_cause_flag
        from ..obs import events as obs_events

        obs_events.install(args.journal, run_id=run_id,
                           max_bytes=args.journal_max_bytes)
        obs_events.emit("run_start", role="serve",
                        experiment=args.experiment, pid=os.getpid(),
                        cause=parse_cause_flag(args.cause))
        info("Run journal to %r (run_id %s)" % (args.journal, run_id))

    with Context("load"):
        experiment = models.instantiate(args.experiment, args.experiment_args)
        replicas, sources, custody_verified, served_step = load_replicas(args, experiment)
        nb_replicas = len(replicas)
        for index, source in enumerate(sources):
            info("replica %d: %s" % (index, source))
        if custody_verified is not None:
            info("chain of custody: %s" % (
                "VERIFIED (every replica's lineage manifest checks out)"
                if custody_verified else
                "UNVERIFIED (unsigned checkpoint allowed by --allow-unsigned)"
            ))
        vote = None
        if args.gar != "none" and nb_replicas > 1:
            f = args.replica_byz if args.replica_byz is not None else (nb_replicas - 1) // 2
            vote = gars.instantiate(args.gar, nb_replicas, f, list(args.gar_args))
        elif args.gar != "none" and args.poison_replica:
            raise UserException(
                "Poisoned single-replica serving has no vote to mask the fault; "
                "use --replicas >= 2 (R >= 2f+1 for median)"
            )
        buckets = None
        if args.buckets:
            buckets = [int(b) for b in args.buckets.split(",")]

    with Context("compile"):
        engine = InferenceEngine(
            experiment, replicas, gar=vote, max_batch=args.max_batch,
            buckets=buckets, seed=args.seed, weights_step=served_step,
        )
        if not args.no_warmup:
            engine.warmup()

    summaries = SummaryWriter(args.summary_dir, run_name="serve", run_id=run_id)
    server = InferenceServer(
        engine, host=args.host, port=args.port,
        queue_bound=args.queue_bound,
        lanes=args.lanes, max_lanes=args.max_lanes,
        linger_s=args.linger_ms / 1e3,
        summaries=summaries,
        request_timeout_s=args.request_timeout,
        flag_threshold=args.flag_threshold,
        custody_verified=custody_verified,
    )

    def reload_step(step):
        """The weight pipeline's reload: re-restore every replica at
        ``step`` through the full custody path (poison specs re-applied),
        swap atomically, update /healthz's verdict.  Raising keeps the
        previous weights serving (CheckpointWatcher's contract)."""
        fresh, fresh_sources, fresh_custody, _ = load_replicas(
            args, experiment, step=step
        )
        engine.swap_replicas(fresh, step=step)
        server.set_custody_verified(fresh_custody)
        for index, source in enumerate(fresh_sources):
            info("hot swap: replica %d <- %s" % (index, source))

    def poll_steps():
        """Steps available in EVERY checkpoint directory (a multi-dir pool
        only swaps when all its sources reached the step)."""
        base_name = (args.checkpoint_base_name
                     if args.checkpoint_base_name is not None
                     else config.default_checkpoint_base_name)
        common = None
        for directory in dict.fromkeys(args.ckpt_dir):
            steps = set(Checkpoints(directory, base_name).steps())
            common = steps if common is None else (common & steps)
        return sorted(common or ())

    watcher = CheckpointWatcher(
        poll_steps, reload_step, served_step=served_step,
        interval_s=args.follow_interval, summaries=summaries,
    )
    autoscaler = None
    if args.autoscale:
        autoscaler = PoolAutoscaler(server, AutoscaleConfig(args.autoscale_args))

    from ..obs import events as obs_events

    stop = threading.Event()
    draining = threading.Event()

    def on_signal(signum, frame):
        info("Signal %d: immediate shutdown" % signum)
        stop.set()

    def on_drain(signum, frame):
        # SIGTERM = the fleet-clean exit: /status flips ``draining`` so the
        # router stops sending NEW traffic here, in-flight requests (and any
        # stragglers that race the scrape window) finish, and we leave at
        # quiescence — bounded by --drain-timeout so a wedged queue cannot
        # hold the process hostage.
        if draining.is_set():
            info("Signal %d: already draining; forcing shutdown" % signum)
            stop.set()
            return
        draining.set()
        info("Signal %d: draining (timeout %gs)" % (signum, args.drain_timeout))
        server.begin_drain()

        def wait_quiescent():
            obs_events.emit("serve_drain", phase="begin",
                            in_flight=server.scheduler.in_flight,
                            queue_depth=server.scheduler.queue_depth)
            deadline = time.monotonic() + args.drain_timeout
            while time.monotonic() < deadline and not server.is_quiescent():
                time.sleep(0.05)
            obs_events.emit("serve_drain", phase="finished",
                            quiescent=server.is_quiescent())
            stop.set()

        threading.Thread(target=wait_quiescent, daemon=True,
                         name="serve-drain").start()

    def on_reload(signum, frame):
        # off the signal handler: a reload restores checkpoints (seconds of
        # work) and the watcher lock serializes it against the poll thread
        info("Signal %d: hot checkpoint restore" % signum)
        threading.Thread(
            target=watcher.check_once, kwargs={"force": True}, daemon=True
        ).start()

    previous = {
        signal.SIGINT: signal.signal(signal.SIGINT, on_signal),
        signal.SIGTERM: signal.signal(signal.SIGTERM, on_drain),
        signal.SIGHUP: signal.signal(signal.SIGHUP, on_reload),
    }
    try:
        host, port = server.serve_background()
        if args.follow:
            watcher.start()
            info("weight pipeline: following %r every %gs (served step %r)"
                 % (list(args.ckpt_dir), args.follow_interval, served_step))
        if autoscaler is not None:
            autoscaler.start()
            info("autoscale: %d capacity rung(s), starting at %d"
                 % (len(autoscaler.ladder), autoscaler.rung))
        # The handshake contract: by the time the ready file exists, the
        # bucket ladder is compiled (warmup ran above, unless explicitly
        # skipped) and the port accepts connections — a smoke's first
        # request never races a cold bucket.
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as fd:
                fd.write("%s %d %d\n" % (host, port, os.getpid()))
            os.replace(tmp, args.ready_file)  # atomic: readers never see a torn line
        info("Serving %s on http://%s:%d (%d replica(s), vote=%s)"
             % (args.experiment, host, port, nb_replicas,
                type(vote).__name__ if vote else "none"))
        stop.wait()
    finally:
        for signum, handler in previous.items():
            signal.signal(signum, handler)
        if autoscaler is not None:
            autoscaler.close()
        watcher.close()
        server.shutdown_all()
        summaries.close()
        if args.journal:
            from ..obs import events as obs_events

            if obs_events.installed() is not None:
                obs_events.emit("run_end", role="serve")
                written = obs_events.uninstall()
                info("Run journal -> %r (run_id %s)" % (written, run_id))
        if args.trace_file:
            written = trace.uninstall(save=True)
            if written:
                info("Trace written to %r (run_id %s)" % (written, run_id))
    return 0


def cli():
    from . import console_entry

    return console_entry(main)


if __name__ == "__main__":
    sys.exit(cli())

"""Training runner: the reference's ``runner.py`` re-based on the SPMD engine.

Argument-compatible surface (reference: runner.py:80-231): experiment /
aggregator selection with ``key:value`` sub-args, n/f worker counts and their
sanity checks (runner.py:253-260), optimizer + learning-rate registries,
l1/l2 regularization (graph.py:125-139), attack plumbing (implementing the
TODO at runner.py:345), lossy-UDP worker simulation (deploy.py:119-122),
evaluation / checkpoint / summary cadences (config.py:54-61), NaN-loss
divergence abort (runner.py:570-574) and the end-of-run performance report
with the first (compilation) step excluded (runner.py:586-598).

What is *gone*, by design: cluster specs, job names, tf.train.Server
plumbing — one SPMD program over a device mesh replaces the PS/worker
process topology.  Multi-host runs wrap this same runner with
``cli.deploy`` (jax.distributed) instead of SSH'd server processes.

Example::

  python3 -m aggregathor_tpu.cli.runner --experiment mnist --aggregator krum \
      --nb-workers 8 --nb-decl-byz-workers 2 --max-step 2000 \
      --learning-rate-args initial-rate:0.05 --evaluation-period 10
"""

import argparse
import contextlib
import os
import signal
import sys
import time


def build_parser():
    parser = argparse.ArgumentParser(
        prog="aggregathor-tpu runner", description="Byzantine-resilient SPMD training on TPU"
    )
    # Experiment / aggregation (reference: runner.py:94-137)
    parser.add_argument("--experiment", required=True, help="experiment name (see models registry)")
    parser.add_argument("--experiment-args", nargs="*", default=[], help="key:value experiment arguments")
    parser.add_argument("--aggregator", required=True, help="GAR name (see gars registry)")
    parser.add_argument("--aggregator-args", nargs="*", default=[], help="key:value GAR arguments")
    parser.add_argument("--nb-workers", type=int, required=True, help="number n of logical workers")
    parser.add_argument("--nb-decl-byz-workers", type=int, default=0, help="declared Byzantine count f")
    parser.add_argument("--nb-real-byz-workers", type=int, default=0, help="actual attacking worker count")
    parser.add_argument("--attack", default=None, help="gradient attack name (reference TODO runner.py:345)")
    parser.add_argument("--attack-args", nargs="*", default=[], help="key:value attack arguments")
    # Optimization (reference: runner.py:157-183)
    parser.add_argument("--optimizer", default="sgd", help="optimizer name")
    parser.add_argument("--optimizer-args", nargs="*", default=[], help="key:value optimizer arguments")
    parser.add_argument("--learning-rate", default="fixed", help="learning-rate schedule name")
    parser.add_argument("--learning-rate-args", nargs="*", default=[], help="key:value schedule arguments")
    parser.add_argument("--l1-regularize", type=float, default=None, help="l1 loss regularization")
    parser.add_argument("--l2-regularize", type=float, default=None, help="l2 loss regularization")
    parser.add_argument("--max-step", type=int, default=None, help="train step count (default config.py)")
    parser.add_argument(
        "--unroll", type=int, default=1,
        help="scan this many steps per dispatch (cadences then fire at chunk granularity)",
    )
    parser.add_argument(
        "--exchange-dtype", default=None, choices=["float32", "bfloat16"],
        help="wire precision of the gradient exchange (bfloat16 halves the "
             "collective bytes; GAR math stays float32).  Subsumed by "
             "--exchange, which also reaches int8/top-k",
    )
    parser.add_argument(
        "--exchange", default=None, metavar="SPEC",
        help="wire codec of the gradient exchange (parallel/compress.py, "
             "docs/engine.md 'The wire'): f32 | bf16 | int8[:ef] | "
             "topk:k=K[,ef] | topk:frac=F[,ef].  int8 quantizes each row "
             "symmetrically with a traced per-row scale (~4x fewer bytes); "
             "topk ships only the k largest-|value| coordinates; ef adds "
             "per-worker error feedback (the residual rides TrainState.ef, "
             "checkpointed).  Rows are encoded after the worker-local "
             "attacks and decoded at the aggregation boundary, so every "
             "GAR sees float32; digests sign the wire image; "
             "bytes_on_wire_total / exchange_compression_ratio land on the "
             "metrics registry.  int8/topk need the flat engine and refuse "
             "--secure-mask (the fixed-point pads need the exact rows)",
    )
    parser.add_argument(
        "--worker-momentum", type=float, default=None, metavar="BETA",
        help="workers send momenta (beta in (0,1)) instead of raw gradients — "
             "history-aware robustness (Karimireddy et al. 2021)",
    )
    parser.add_argument(
        "--mesh", default=None, metavar="W,PP,TP",
        help="route training through the fully-sharded engine on a logical "
             "(worker x pipeline x tensor) mesh: per-layer robust aggregation "
             "on sharded gradients, the (n, d) matrix never materialized "
             "(needs an experiment that publishes sharded hooks, e.g. "
             "transformer). W must equal --nb-workers.",
    )
    parser.add_argument(
        "--microbatches", type=int, default=None,
        help="pipeline microbatches per step (sharded engine only; "
             "default 2).  Rejected under sharded --step-deadline: the "
             "bounded submission body computes per-worker FULL-batch "
             "gradients over experiment.loss, so the knob would be dead",
    )
    parser.add_argument(
        "--granularity", default="vector", choices=["vector", "leaf", "layer", "global"],
        help="apply the rule to the whole flattened gradient (vector — the "
             "reference's semantics, graph.py:144-168) or per parameter "
             "leaf (leaf — per-layer selection; each layer picks its own "
             "honest set)",
    )
    parser.add_argument(
        "--leaf-bucketing", default="auto", choices=["auto", "on", "off"],
        help="granularity:leaf implementation: bucket same-shaped leaves "
             "into one vmapped rule call per distinct size (the TPU-shaped "
             "program) or loop per leaf (faster on XLA:CPU — measured, "
             "BENCHMARKS.md row 6b). auto picks by backend; the two paths "
             "make identical selections (same per-leaf PRNG keys) and agree "
             "numerically to float tolerance",
    )
    parser.add_argument(
        "--reputation-decay", type=float, default=None, metavar="BETA",
        help="track a per-worker reputation EMA (1 = trusted) of a rank "
             "signal: was the worker's raw gradient among the n-f closest "
             "to the applied aggregate this step",
    )
    parser.add_argument(
        "--quarantine-threshold", type=float, default=0.0, metavar="T",
        help="workers whose reputation falls below T are excluded from "
             "aggregation (row masked NaN — needs a NaN-tolerant rule); "
             "they are re-admitted automatically when their raw gradients "
             "re-approach the aggregate (requires --reputation-decay)",
    )
    parser.add_argument(
        "--worker-metrics", action="store_true",
        help="record per-worker suspicion diagnostics each summary: squared "
             "distance to the aggregate and, for selection rules, the "
             "worker's participation weight (detects persistent attackers)",
    )
    parser.add_argument(
        "--gar-probe", action="store_true",
        help="measure the GAR's wall time at each summary fire: one jitted "
             "rule-only aggregation at the run's exact (n, d) is timed under "
             "a gar.aggregate span and exported as gar_seconds_total / "
             "gar_probe_seconds on the metrics registry (the cost model "
             "behind docs/gar_scaling.md, measured instead of presumed; "
             "compiled once, outside the training step's jit cache)",
    )
    parser.add_argument(
        "--prefetch", type=int, default=2, metavar="DEPTH",
        help="device-ready input batches/chunks prepared ahead of the "
             "training dispatch (0 disables): per-step runs use a "
             "background prefetch thread, --unroll runs the three-stage "
             "chunk pipeline (parallel sharded gather into ping-pong "
             "buffers, sliced async transfer, device-side assemble — "
             "docs/input_pipeline.md)",
    )
    parser.add_argument(
        "--input-slices", type=int, default=4, metavar="S",
        help="transfer slices per --unroll chunk in the input pipeline: "
             "each slice's host->device copy is issued as soon as it is "
             "gathered, so the wire starts moving after 1/S of the chunk "
             "(1 = one monolithic transfer per chunk)",
    )
    parser.add_argument(
        "--input-source", default="stream", choices=["stream", "device"],
        help="stream: per-step host batches (the reference's input path, "
             "runner.py:562-576). device: hold the training split on the "
             "accelerator (transferred once) and gather each worker's fresh "
             "i.i.d. batch in-graph — removes the per-step host->device "
             "transfer that bounds a tunneled TPU (measured r4: config 2 at "
             "2.0 steps/s streamed vs 26 resident); needs an experiment "
             "exposing train_arrays() (no host-side transform) and the flat "
             "engine, single process",
    )
    parser.add_argument(
        "--step-deadline", type=float, default=None, metavar="SECONDS",
        help="bounded-wait aggregation (parallel/bounded.py, docs/engine.md): "
             "dispatch each worker's gradient as its own async submission "
             "and close every round at this host-side deadline — workers "
             "that miss it contribute NaN rows within the same declared-f "
             "budget as Byzantine rows (timeouts + attacks <= f), land as "
             "straggler_timeout forensics evidence, and sustained "
             "over-budget timeouts are a guardian escalation input.  Needs "
             "the flat engine, --unroll 1, a NaN-tolerant rule, and no "
             "in-graph transport simulation (--UDP/non-straggler --chaos)",
    )
    parser.add_argument(
        "--topology", default=None, metavar="SPEC",
        help="aggregation-tree topology (topology/, docs/topology.md): "
             "replace the PS star with L levels of untrusted sub-"
             "aggregators, e.g. tree:g=16x4,rules=median>trimmed-mean>"
             "krum,link=int8,redundancy=2,agg-f=1x0.  The tree IS the "
             "aggregation rule (pass --aggregator tree; the spec "
             "substitutes into the guardian's Overrides record): "
             "f-budgets compose through the levels at parse time, every "
             "inter-level link rides the declared wire codec, each level "
             "closes its own bounded-wait round, sub-aggregator custody "
             "is chain-verified (a forged emission NAMES its (level, "
             "unit) in forensics — never laundered into worker blame), "
             "and redundancy=r serves a faulted unit from a sibling "
             "shadow.  Needs the flat engine; implies bounded-wait "
             "dispatch (add --step-deadline for real per-level windows)",
    )
    parser.add_argument(
        "--straggler-stall", type=float, default=0.0, metavar="SECONDS",
        help="bounded-wait straggler injection: a worker drawn late holds "
             "its submission this long before dispatching (the chaos "
             "straggler regimes' wall-clock twin; with --chaos the per-"
             "regime straggle rates schedule WHO is late, otherwise "
             "--straggler-rate does)",
    )
    parser.add_argument(
        "--straggler-rate", type=float, default=0.0, metavar="P",
        help="bounded-wait: flat per-(step, worker) lateness probability "
             "when no --chaos schedule provides regime rates",
    )
    parser.add_argument(
        "--straggler-jitter", type=float, default=0.0, metavar="SIGMA",
        help="bounded-wait straggler injection: heavy-tail the stall — a "
             "late worker sleeps stall * exp(SIGMA * N(0,1)) (lognormal, "
             "median = --straggler-stall) instead of exactly the stall; "
             "with --chaos the per-regime jitter=SIGMA takes precedence",
    )
    parser.add_argument(
        "--deadline-percentile", type=float, default=None, metavar="P",
        help="adaptive bounded-wait window (parallel/deadline.py, "
             "docs/engine.md): track the per-worker arrival distribution "
             "and set each round's window to its P-th percentile, "
             "EMA-smoothed and clamped into [--deadline-floor, "
             "--deadline-ceiling].  Requires --step-deadline (the initial "
             "window and the default ceiling).  Choose P at or below "
             "100*(n-f-1)/(n-1) (e.g. 71.4 for n=8, f=2) so a persistent "
             "straggler coalition inside the declared budget cannot pin "
             "the window at the ceiling",
    )
    parser.add_argument(
        "--deadline-floor", type=float, default=0.01, metavar="SECONDS",
        help="adaptive deadline: smallest window the controller may emit",
    )
    parser.add_argument(
        "--deadline-ceiling", type=float, default=None, metavar="SECONDS",
        help="adaptive deadline: largest window (default: --step-deadline "
             "— the fixed protocol's declared worst-case wait); a "
             "controller pinned here for ceiling-patience steps is a "
             "guardian escalation input",
    )
    parser.add_argument(
        "--deadline-ema", type=float, default=0.3, metavar="ALPHA",
        help="adaptive deadline: weight of each new round's percentile "
             "target in (0, 1] — smoothing so a single spiked round "
             "cannot whipsaw the window",
    )
    parser.add_argument(
        "--stale-infill", action="store_true",
        help="bounded-wait: a timed-out worker re-enters its CLEVER carry "
             "row (the last submission this aggregator received from it) "
             "instead of a NaN drop.  Stale rows SPEND the declared-f "
             "budget exactly like timeouts and attacks (stale + timeouts "
             "+ attacks <= f — a Byzantine straggler re-enters its carried "
             "attack row), and land as stale_infill forensics evidence",
    )
    parser.add_argument(
        "--stale-max-age", type=int, default=4, metavar="ROUNDS",
        help="bounded-wait stale infill: a carry older than this many "
             "consecutive missed rounds degrades back to a NaN drop",
    )
    parser.add_argument(
        "--stale-reweight", action="store_true",
        help="bounded-wait v3: damp each stale carry row by its age — a "
             "carry of age a enters aggregation scaled by 1/(1+a) (the "
             "unbiased-estimator framing of arXiv:2505.23523) instead of "
             "at full weight.  Requires --stale-infill; the damped row "
             "still SPENDS the declared-f budget, and every reweighted "
             "re-entry is a stale_reweight journal event",
    )
    parser.add_argument(
        "--incremental-aggregation", action="store_true",
        help="bounded-wait: fold each submission's decoded row into the "
             "aggregate-side device buffer the instant it lands instead of "
             "stacking at the round barrier — decode/transfer overlaps the "
             "submissions still outstanding (exchange_overlap_fraction on "
             "the registry measures it).  Needs --step-deadline and the "
             "flat engine; numerics identical to the stacked path",
    )
    parser.add_argument(
        "--backend-timeout", type=float, default=300.0, metavar="SECONDS",
        help="fail loudly if the accelerator backend does not initialize in "
             "this many seconds (a wedged chip otherwise hangs forever); "
             "<= 0 waits indefinitely",
    )
    parser.add_argument("--seed", type=int, default=0, help="base PRNG seed")
    parser.add_argument(
        "--session-secret", default=None, metavar="SECRET",
        help="shared secret authenticating the multi-host boundary: every "
             "process HMAC-tags a digest of its post-init parameters and "
             "verifies every peer's tag at bring-up; any process launched "
             "without the secret (or with a tampered payload) aborts the "
             "cluster (reference: signed worker->PS pushes + TLS channels, "
             "mpi_rendezvous_mgr.patch:585-627, grpc_channel.patch:70-85)",
    )
    parser.add_argument(
        "--secure", action="store_true",
        help="authenticated gradient submission (secure/, docs/security.md): "
             "every worker's per-step row is digest-tagged under a per-"
             "(worker, step) HMAC key from --session-secret, verified before "
             "aggregation; a failed tag becomes a NaN row AND a named "
             "'forgery' forensics evidence entry (reject-and-name); custody "
             "manifests are written beside every checkpoint and verified on "
             "restore; zero added recompiles (requires --session-secret)",
    )
    parser.add_argument(
        "--secure-mask", action="store_true",
        help="bucket-level additive masking (Bonawitz-style, secure/"
             "masking.py): individual gradient rows are one-time-padded and "
             "the pads cancel EXACTLY inside bucket/hier group means — "
             "requires a mean-inner meta-GAR spec (bucketing:..., or "
             "hier:inner=average,...) and --session-secret; a worker that "
             "drops mid-step NaNs its whole group",
    )
    parser.add_argument(
        "--allow-unsigned", action="store_true",
        help="let a --secure run restore checkpoints that carry NO custody "
             "manifest (e.g. resuming a directory written before --secure "
             "was enabled): provenance is then unverified for that restore; "
             "new snapshots are signed as usual",
    )
    parser.add_argument(
        "--no-legacy-checkpoint-tags", action="store_true",
        help="refuse snapshots tagged under the pre-context-separation key "
             "scheme instead of accepting + re-tagging them once; set this "
             "when no pre-upgrade snapshots exist to close the downgrade "
             "acceptance entirely",
    )
    parser.add_argument(
        "--encrypt-checkpoints", action="store_true",
        help="encrypt snapshot bytes at rest under a key derived from "
             "--session-secret (SHAKE-256 keystream, encrypt-then-MAC with "
             "the HMAC tag) — the framework-side counterpart of the "
             "reference's TLS channels (grpc_channel.patch:70-85) for state "
             "that outlives the run; requires --session-secret",
    )
    # Cadences (reference: runner.py:184-215)
    parser.add_argument("--evaluation-file", default=None, help="TSV evaluation log path")
    parser.add_argument("--evaluation-delta", type=int, default=None, help="eval every this many steps")
    parser.add_argument("--evaluation-period", type=float, default=None, help="eval every this many seconds")
    parser.add_argument("--checkpoint-dir", default=None, help="checkpoint directory")
    parser.add_argument("--checkpoint-base-name", default=None, help="checkpoint file base name")
    parser.add_argument("--checkpoint-delta", type=int, default=None)
    parser.add_argument("--checkpoint-period", type=float, default=None)
    parser.add_argument("--checkpoint-keep", type=int, default=5, help="snapshots to keep")
    parser.add_argument("--summary-dir", default=None, help="JSONL scalar summary directory")
    parser.add_argument("--summary-delta", type=int, default=None)
    parser.add_argument("--summary-period", type=float, default=None)
    # Transport simulation + tracing (reference: deploy.py:119-122, runner.py:216-219)
    parser.add_argument("--UDP", type=int, default=0, dest="udp", help="first k workers use the lossy link")
    parser.add_argument("--UDP-args", nargs="*", default=[], dest="udp_args", help="key:value lossy-link arguments")
    parser.add_argument(
        "--chaos", default=None, metavar="SCHEDULE",
        help="time-varying fault-regime schedule (chaos/ DSL, e.g. "
             "'0:calm 500:drop=0.3 1000:attack=empire'): regime switches "
             "happen inside the jitted step with zero recompilation; "
             "subsumes the static --attack/--UDP knobs",
    )
    parser.add_argument(
        "--chaos-args", nargs="*", default=[],
        help="key:value schedule-wide chaos options (packet-coords:N, "
             "min-coords:N, straggle-workers:K)",
    )
    parser.add_argument(
        "--guardian", action="store_true",
        help="in-loop divergence watchdog + rollback-and-escalate recovery "
             "(guardian/, docs/guardian.md): on sustained divergence, restore "
             "the last-known-good snapshot, perturb the RNG and climb the "
             "escalation ladder (raise f -> stronger GAR -> quarantine -> "
             "damp lr) with bounded retries; needs --checkpoint-dir",
    )
    parser.add_argument(
        "--guardian-args", nargs="*", default=[],
        help="key:value watchdog options (patience:N, spike:X, retries:N, "
             "backoff:B, recover:N, ladder:RUNG,RUNG,... — see "
             "docs/guardian.md for the ladder grammar)",
    )
    parser.add_argument("--trace", action="store_true", help="capture a jax.profiler trace of a few steps")
    parser.add_argument("--trace-dir", default="trace", help="profiler trace output directory")
    parser.add_argument(
        "--trace-file", default=None, metavar="PATH",
        help="whole-run HOST span trace (obs/trace): dispatch / block / "
             "host-gap / input / eval / checkpoint spans as Chrome "
             "trace-event JSON, Perfetto-loadable; zero added recompiles, "
             "bounded overhead (benchmarks/trace_overhead.py); "
             "multi-process runs suffix non-lead files with .<process>",
    )
    parser.add_argument(
        "--forensics", default=None, metavar="JSON",
        help="write a Byzantine forensics attribution report here at exit "
             "(schema aggregathor.obs.forensics.v1, plus a .md rendering): "
             "a per-worker suspicion timeline built from the engines' "
             "per-step diagnostics + guardian verdicts + chaos regime "
             "context (docs/observability.md); implies --worker-metrics",
    )
    parser.add_argument(
        "--journal", default=None, metavar="JSONL",
        help="causal run journal (obs/events.py, docs/observability.md "
             "'The control room'): append every decision event — guardian "
             "rollbacks/escalations, deadline-window moves, bounded-wait "
             "timeouts/stale infill, forgery verdicts, flight post-mortems "
             "— as typed JSONL (schema aggregathor.obs.events.v2) with "
             "run_id, step, wall+monotonic time; cross-referenced from the "
             "forensics report and served fleet-wide by obs/fleet.py; "
             "host-side only, zero added recompiles; lead process only",
    )
    parser.add_argument(
        "--metrics-file", default=None, metavar="PATH",
        help="dump the process-wide metrics registry as Prometheus text "
             "exposition here at every summary fire and at exit (the "
             "training-side counterpart of serve's /metrics endpoint); the "
             "final flush runs on normal exit, SIGTERM and divergence alike",
    )
    from . import add_causal_flags

    add_causal_flags(parser)
    parser.add_argument(
        "--flight", type=int, default=0, metavar="CAPACITY",
        help="flight recorder (obs/flight.py, docs/observability.md): carry "
             "a CAPACITY-row ring of per-step telemetry lanes (loss, update "
             "norm, probe flags, per-worker distances/NaN rows, chaos "
             "regime, secure verdicts) as a device-side TrainState buffer "
             "written INSIDE the jitted scan, fetched once per summary fire "
             "and dumped post-mortem on rollback/crash; zero added "
             "recompiles; 0 disables",
    )
    parser.add_argument(
        "--flight-dump", default=None, metavar="JSON",
        help="write the flight-recorder window here on guardian rollback or "
             "crash (schema aggregathor.obs.flight.v1) — exact per-step "
             "evidence for the window that killed the run; rollback dumps "
             "suffix .rollback-<step> before the extension (requires "
             "--flight)",
    )
    parser.add_argument(
        "--xprof", default=None, metavar="A:B",
        help="programmatic jax.profiler device capture over steps [A, B) "
             "into --trace-dir (obs/profiler.py): dispatches inside the "
             "window carry StepTraceAnnotations so the host span trace "
             "joins the device timeline per step; under --unroll the "
             "window lands on chunk boundaries (mutually exclusive with "
             "--trace)",
    )
    parser.add_argument(
        "--live-port", type=int, default=None, metavar="PORT",
        help="serve a live exporter for THIS training run (obs/live.py): "
             "/metrics (Prometheus text of the one registry), /status "
             "(step progress, steps/s, the latest flight window, the SLO "
             "verdict), /healthz; 0 binds an ephemeral port; lead process "
             "only",
    )
    parser.add_argument(
        "--live-host", default="127.0.0.1", metavar="HOST",
        help="bind address of the live exporter",
    )
    parser.add_argument(
        "--live-ready-file", default=None, metavar="PATH",
        help="write 'host port' here once the live exporter is bound (the "
             "smoke scripts' handshake; requires --live-port)",
    )
    parser.add_argument(
        "--slo-baseline", default=None, metavar="JSON",
        help="regression sentinel (obs/slo.py): load this baseline document "
             "(schema aggregathor.obs.slo.v1, seeded via --slo-capture on a "
             "healthy run) and emit a PASS/REGRESS verdict on steps/s, "
             "gar_seconds_total and input_overlap_fraction at run end (an "
             "slo_verdict summary event + info line)",
    )
    parser.add_argument(
        "--slo-verdict", default=None, metavar="JSON",
        help="also write the sentinel verdict document here (requires "
             "--slo-baseline)",
    )
    parser.add_argument(
        "--slo-capture", default=None, metavar="JSON",
        help="capture THIS run's end-state throughput metrics as a fresh "
             "SLO baseline document here (what --slo-baseline loads)",
    )
    parser.add_argument(
        "--run-id", default=None, metavar="ID",
        help="run id stamped on every summary line, the trace metadata and "
             "the forensics report so the streams join after the fact "
             "(default: generated)",
    )
    parser.add_argument("--trace-ops", action="store_true",
                        help="per-op terminal narrative: print a marker after "
                             "each phase of the step body (gradients, "
                             "aggregate, apply) — the reference's op-bracket "
                             "trace (tools/tf.py:41-58); debug cadence only")
    # Mesh (replaces cluster/job flags, reference: runner.py:81-93, 220-231)
    parser.add_argument("--nb-devices", type=int, default=None, help="devices on the worker mesh axis")
    parser.add_argument("--platform", default=None, help="force a JAX platform (tpu/cpu)")
    parser.add_argument("--stdout-to", default=None, help="replicate stdout to this file")
    parser.add_argument("--stderr-to", default=None, help="replicate stderr to this file")
    # Device-preference flags (reference: runner.py:196-211): map to a JAX
    # platform priority list when --platform is not forced.
    parser.add_argument("--use-tpu", action="store_true", help="prefer TPU devices if available")
    parser.add_argument("--use-gpu", action="store_true", help="prefer GPU devices if available")
    parser.add_argument("--reuse-tpu", action="store_true",
                        help="compat: implies --use-tpu (device sharing is inherent under SPMD)")
    parser.add_argument("--reuse-gpu", action="store_true",
                        help="compat: implies --use-gpu (device sharing is inherent under SPMD)")
    # Drop-in compatibility: flags whose mechanism dissolved under the
    # single-controller SPMD design (docs/transport.md) — accepted so the
    # reference's driver scripts run unchanged, warned about once.
    for flag, meta in (
        ("--client", "TARGET"), ("--server", "SPEC"), ("--ps-job-name", "NAME"),
        ("--ev-job-name", "NAME"), ("--wk-job-name", "NAME"),
    ):
        parser.add_argument(flag, default=None, metavar=meta,
                            help="compat no-op: cluster/session topology dissolved under SPMD")
    parser.add_argument("--MPI", action="store_true", dest="mpi",
                        help="compat no-op: transport is XLA collectives over ICI/DCN")
    parser.add_argument("--no-wait", action="store_true",
                        help="compat no-op: there is no server process to linger")
    return parser


def main(argv=None):
    args = build_parser().parse_args(argv)
    mesh_axes = None
    if args.mesh:
        try:
            mesh_axes = tuple(int(x) for x in args.mesh.split(","))
            if len(mesh_axes) != 3 or any(a < 1 for a in mesh_axes):
                raise ValueError
        except ValueError:
            from ..utils import UserException

            raise UserException("--mesh wants W,PP,TP positive integers (got %r)" % args.mesh)
    device_preference = None
    if not args.platform and (args.use_tpu or args.use_gpu or args.reuse_tpu or args.reuse_gpu):
        # preference order like the reference's allocator (runner.py:282-287):
        # TPU > GPU > CPU among the requested kinds, CPU always the fallback
        device_preference = []
        if args.use_tpu or args.reuse_tpu:
            device_preference.append("tpu")
        if args.use_gpu or args.reuse_gpu:
            device_preference.append("gpu")
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform

    # Heavy imports after the platform choice is pinned.
    import jax
    import jax.numpy as jnp
    import numpy as np

    # How many devices this run needs: the flat engine's worker axis, or the
    # full W*PP*TP product of a --mesh request.
    requested_devices = mesh_axes[0] * mesh_axes[1] * mesh_axes[2] if mesh_axes else args.nb_devices

    def want_cpu_devices():
        # The virtual-CPU device count must be configured BEFORE any backend
        # initializes (a post-init update raises); honor an ambient
        # XLA_FLAGS force if one exists.
        return (
            requested_devices and requested_devices > 1
            and "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", "")
        )

    if args.platform:
        # The env var alone can be ignored when an accelerator plugin is
        # pinned by the surrounding environment; the config update wins as
        # long as no backend has been initialized yet (tests/conftest.py has
        # the same dance).
        jax.config.update("jax_platforms", args.platform)
        if args.platform == "cpu" and want_cpu_devices():
            jax.config.update("jax_num_cpu_devices", requested_devices)
    elif device_preference is not None:
        # "use X if available" (reference allocator semantics): try the
        # preference list; when this installation cannot even name the
        # backend, fall through to CPU like the reference does when no such
        # device exists in the cluster.  The probe initializes a backend, so
        # the CPU device count is set first (the fallback may land there).
        if want_cpu_devices():
            jax.config.update("jax_num_cpu_devices", requested_devices)
        # JAX's platform list is strict (one uninitializable backend fails the
        # whole list), so retry progressively shorter suffixes: a GPU host
        # without libtpu still lands on its GPU, not on CPU.
        candidates = device_preference + ["cpu"]
        for start in range(len(candidates)):
            args.platform = ",".join(candidates[start:])
            jax.config.update("jax_platforms", args.platform)
            try:
                jax.devices()
                break
            except RuntimeError:
                continue
    else:
        effective_platform = os.environ.get("JAX_PLATFORMS", "")
        if effective_platform:
            # Mirror the env var at the config level: the env filter alone
            # is applied AFTER accelerator-plugin discovery, and a wedged
            # tunneled plugin can hang that discovery forever (measured r4:
            # ``JAX_PLATFORMS=cpu jax.devices()`` blocked indefinitely while
            # the TPU tunnel was wedged; with the config update it returned
            # the CPU immediately).
            jax.config.update("jax_platforms", effective_platform)
        if effective_platform == "cpu" and want_cpu_devices():
            jax.config.update("jax_num_cpu_devices", requested_devices)

    from .. import config, gars, models
    from ..core import build_optimizer, build_schedule
    from ..obs import (
        CadenceTrigger,
        Checkpoints,
        EvalFile,
        ForensicsLedger,
        PerfReport,
        SummaryWriter,
        trace,
    )
    from ..obs import events as obs_events
    from ..obs import flight as obs_flight
    from ..obs import live as obs_live
    from ..obs import metrics as obs_metrics
    from ..obs import profiler as obs_profiler
    from ..obs import slo as obs_slo
    from ..obs.summaries import make_run_id
    from ..parallel import RobustEngine, attacks, make_mesh
    from ..parallel import compress
    from ..parallel.lossy import LossyLink
    from ..utils import Context, UserException, info, replicate_streams, warning

    replicate_streams(args.stdout_to, args.stderr_to)

    run_id = args.run_id if args.run_id else make_run_id()
    registry = obs_metrics.REGISTRY
    if (args.secure or args.secure_mask) and not args.session_secret:
        raise UserException(
            "--secure/--secure-mask derive their per-worker keys and mask "
            "pads from --session-secret; pass it"
        )
    # The wire codec (--exchange, parallel/compress.py): parsed up front so
    # a bad spec or an infeasible composition fails before any compilation.
    exchange_codec = None
    if args.exchange:
        if args.exchange_dtype:
            raise UserException(
                "--exchange generalizes --exchange-dtype (bf16 is spelled "
                "--exchange bf16); pass only one"
            )
        spec_dtype, exchange_codec = compress.parse_exchange_spec(args.exchange)
        if spec_dtype is not None:
            # bf16 normalizes onto the historical dtype twin (works on
            # BOTH engines, bit-compatible with existing runs)
            args.exchange_dtype = "bfloat16"
            args.exchange = None
    if exchange_codec is not None:
        if args.mesh:
            raise UserException(
                "--exchange %s needs the flat engine (drop --mesh): the "
                "sharded per-(worker, leaf) submissions would need per-leaf "
                "codec state — --exchange bf16 works everywhere"
                % exchange_codec.spec()
            )
        if args.secure_mask:
            raise UserException(
                "--exchange %s + --secure-mask is not supported: the "
                "fixed-point pairwise pads cancel exactly over the EXACT "
                "float32 rows, and a lossy wire codec would corrupt the "
                "cancellation — run masking on the f32/bf16 wire"
                % exchange_codec.spec()
            )
    if args.flight < 0:
        raise UserException("--flight wants a nonnegative ring capacity")
    if args.flight_dump and not args.flight:
        raise UserException("--flight-dump needs --flight CAPACITY")
    if args.live_ready_file and args.live_port is None:
        raise UserException("--live-ready-file needs --live-port")
    if args.slo_verdict and not args.slo_baseline:
        raise UserException("--slo-verdict needs --slo-baseline")
    if args.xprof and args.trace:
        raise UserException(
            "--xprof and --trace both drive the jax.profiler; pick one"
        )
    # Sentinel baseline loads AT STARTUP: a missing/garbled document must
    # fail before an hour of training, not at the verdict.
    sentinel = obs_slo.Sentinel(args.slo_baseline) if args.slo_baseline else None

    # Stop handlers install FIRST (satellite: preempted runs must not exit
    # empty-handed): a SIGTERM during backend init, graph build or the
    # first compile sets the flag, the loop exits at its next check, and
    # the shutdown path flushes --metrics-file/forensics/trace like any
    # normal exit.  The originals are restored at shutdown; a failure
    # before the train loop leaves this benign flag-setter installed only
    # while the process unwinds.
    stop = {"requested": False}

    def on_signal(signum, frame):
        if stop["requested"]:
            # second signal: force-exit escalation — with handlers now
            # installed before backend init, a hung init/compile would
            # otherwise be un-interruptible short of SIGKILL
            warning("Interrupted twice: aborting now")
            raise KeyboardInterrupt
        stop["requested"] = True
        warning("Interrupted: finishing current step then shutting down "
                "(interrupt again to abort immediately)")

    try:
        previous_handlers = {
            signal.SIGINT: signal.signal(signal.SIGINT, on_signal),
            signal.SIGTERM: signal.signal(signal.SIGTERM, on_signal),
        }
    except ValueError:
        # not the main thread (an embedded runner — tests, notebooks):
        # signal handling stays with the host application
        previous_handlers = {}
    if args.forensics and not args.worker_metrics:
        # the ledger's distance evidence rides worker_sq_dist
        info("--forensics implies --worker-metrics: enabling the per-worker "
             "suspicion diagnostics")
        args.worker_metrics = True

    ignored = [flag for flag, value in (
        ("--client", args.client), ("--server", args.server),
        ("--ps-job-name", args.ps_job_name), ("--ev-job-name", args.ev_job_name),
        ("--wk-job-name", args.wk_job_name), ("--MPI", args.mpi), ("--no-wait", args.no_wait),
    ) if value]
    if ignored:
        warning(
            "Compat no-op flags ignored (cluster topology and transport dissolved "
            "under single-controller SPMD, see docs/transport.md): %s" % " ".join(ignored)
        )

    # Worker-count sanity (reference: runner.py:253-260)
    n, f, r = args.nb_workers, args.nb_decl_byz_workers, args.nb_real_byz_workers
    if n < 1:
        raise UserException("Need at least 1 worker (got %d)" % n)
    if r > n:
        raise UserException("More real Byzantine workers (%d) than workers (%d)" % (r, n))
    if r > f:
        warning("More real Byzantine workers (%d) than declared (%d): the GAR bound is void" % (r, f))
    if n <= 2 * f:
        warning("n = %d <= 2f = %d: most GARs offer no guarantee at this ratio" % (n, 2 * f))

    with Context("cluster"):
        if args.backend_timeout and args.backend_timeout > 0:
            # A wedged accelerator can hang backend init indefinitely and
            # uninterruptibly; probe it on a daemon thread so the process
            # can still fail loudly with a diagnosis.
            import threading

            probe_done = threading.Event()
            probe_error = []

            def probe():
                try:
                    jax.devices()
                except BaseException as exc:  # surfaced below
                    probe_error.append(exc)
                finally:
                    probe_done.set()

            threading.Thread(target=probe, daemon=True, name="backend-probe").start()
            if not probe_done.wait(args.backend_timeout):
                raise UserException(
                    "JAX backend did not initialize within %.0fs — the accelerator "
                    "looks wedged or unreachable; retry with --platform cpu or raise "
                    "--backend-timeout" % args.backend_timeout
                )
            if probe_error:
                raise probe_error[0]
        devices = jax.devices()
        if mesh_axes is not None:
            w_axis, pp_axis, tp_axis = mesh_axes
            if n % w_axis != 0:
                raise UserException(
                    "--mesh worker axis W=%d must divide --nb-workers %d "
                    "(k = n/W logical Byzantine workers are vmapped per "
                    "(pipe x model) submesh — the large-n regime, "
                    "docs/gar_scaling.md)" % (w_axis, n)
                )
            mesh = make_mesh(
                nb_workers=w_axis, model_parallelism=tp_axis,
                pipeline_parallelism=pp_axis, devices=devices[:requested_devices],
            )
            info(
                "Sharded mesh: %d worker slot(s) x %d pipeline stage(s) x %d-way "
                "tensor parallelism on %d %s device(s), %d logical worker(s)/slot"
                % (w_axis, pp_axis, tp_axis, requested_devices,
                   devices[0].platform, n // w_axis)
            )
        else:
            nb_devices = args.nb_devices
            if nb_devices is None:
                nb_devices = max(d for d in range(1, len(devices) + 1) if n % d == 0)
            mesh = make_mesh(nb_workers=nb_devices, devices=devices[:nb_devices])
            info(
                "Mesh: %d x %s device(s), %d worker(s)/device"
                % (nb_devices, devices[0].platform, n // nb_devices)
            )

    # Host span tracing (obs/trace.py, docs/observability.md): installed
    # BEFORE the graph/restore phases so their spans are captured too.  Each
    # process writes its own file (suffixed for non-lead processes) — one
    # shared path would clobber.
    if args.trace_file:
        path = args.trace_file
        if jax.process_index() != 0:
            path = "%s.%d" % (path, jax.process_index())
        if args.run_id is None and jax.process_count() > 1:
            # summaries/forensics are lead-only, so the lead's streams still
            # join — but each process GENERATES its own id, so non-lead
            # trace files won't carry the lead's without an explicit id
            warning(
                "Multi-process run without --run-id: per-process trace files "
                "carry independent run_ids; pass --run-id to join them"
            )
        trace.install(path, run_id=run_id)
        info("Span tracing to %r (run_id %s)" % (path, run_id))

    # Causal run journal (obs/events.py): installed BEFORE the graph phase
    # so escalation/deadline/forgery decisions from step 0 on land in one
    # timeline.  Lead-only, like summaries/forensics — the decisions it
    # records are host policy, which is lead-side by construction.
    if args.journal and jax.process_index() == 0:
        from . import parse_cause_flag

        obs_events.install(args.journal, run_id=run_id,
                           max_bytes=args.journal_max_bytes)
        obs_events.emit(
            "run_start", role="train", experiment=args.experiment,
            aggregator=args.aggregator, nb_workers=n, declared_f=f,
            pid=os.getpid(), cause=parse_cause_flag(args.cause),
        )
        info("Run journal to %r (run_id %s)" % (args.journal, run_id))

    # Guardian recovery layer (guardian/, docs/guardian.md): parsed up front
    # so a bad ladder/threshold fails before any compilation.
    from ..guardian import (
        RESEED_STRIDE,
        RNG_PERTURB_TAG,
        GuardianConfig,
        Overrides,
        Watchdog,
        note_escalation,
    )
    from ..guardian import probe as health

    guardian = None
    if args.guardian:
        guardian = GuardianConfig(args.guardian_args)
        if not args.checkpoint_dir:
            raise UserException(
                "--guardian rolls back to on-disk snapshots; pass --checkpoint-dir"
            )
        if jax.process_count() > 1:
            raise UserException(
                "--guardian is single-process for now: rollback decisions would "
                "need a cross-host broadcast to keep the SPMD step counts aligned"
            )
    watchdog = Watchdog(guardian) if guardian is not None else None

    # Aggregation topology (--topology, topology/): the tree spec parses
    # and runs its f-composition arithmetic HERE, before anything compiles,
    # and substitutes for --aggregator in the Overrides record — so a
    # guardian escalation that swaps the rule for a ladder rung also
    # retires the host tree plane (a flat rung has no sub-aggregators to
    # supervise; rolling back to the tree rung reactivates it).
    topology_spec = None
    topology = None
    if args.topology is not None:
        from ..topology import parse_topology_spec

        if args.aggregator != "tree":
            raise UserException(
                "--topology replaces the aggregation rule with the tree "
                "spec; pass --aggregator tree (got %r)" % args.aggregator
            )
        if args.aggregator_args:
            raise UserException(
                "--topology carries the tree's arguments inline "
                "(tree:g=...,rules=...); drop --aggregator-args"
            )
        topology_spec = parse_topology_spec(args.topology, n, f)
        info("Topology: %s" % topology_spec.describe())

    # The escalation ladder overrides exactly these knobs; everything else
    # about the run is immutable.  The training stack is built FROM an
    # Overrides record so a guardian rollback can rebuild it mid-run (one
    # recompile per escalation, paid only on the rare recovery path).
    overrides = Overrides(
        f,
        args.topology if topology_spec is not None else args.aggregator,
        () if topology_spec is not None else tuple(args.aggregator_args),
        reputation_decay=args.reputation_decay,
        quarantine_threshold=args.quarantine_threshold,
    )
    unroll = max(1, args.unroll)

    # Bounded-wait mode flag (parallel/bounded.py), needed before the
    # flight-recorder lane set: under a deadline the chaos schedule moves
    # to the host clock, so the in-graph regime lane does not exist.
    bounded_wait = (args.step_deadline is not None
                    or args.straggler_stall > 0
                    or args.topology is not None)

    # Flight recorder (obs/flight.py): the ring's lane set mirrors exactly
    # what the engine will compute (validated again by the engine itself).
    # Constructed once and shared across guardian rebuilds — the layout is
    # immutable; the BUFFERS are per-state and re-init on every rollback.
    flight_rec = None
    if args.flight:
        flight_rec = obs_flight.FlightRecorder(
            args.flight, n, probe=True, worker_metrics=args.worker_metrics,
            chaos=bool(args.chaos) and not bounded_wait, secure=args.secure,
        )
        if args.flight < unroll:
            warning(
                "--flight capacity %d < --unroll %d: a summary fetch cannot "
                "cover the whole last chunk; size the ring to at least the "
                "unroll (ideally the summary delta)" % (args.flight, unroll)
            )
    # Programmatic profiler window (--xprof A:B): parsed up front so a bad
    # spec fails before any compilation.
    xprof = None
    if args.xprof:
        xprof = obs_profiler.ProfilerWindow(
            args.xprof, args.trace_dir, registry=registry
        )

    with Context("graph"):
        experiment = models.instantiate(args.experiment, args.experiment_args)
        attack = attacks.instantiate(args.attack, n, r, args.attack_args) if args.attack else None
        lossy = LossyLink(args.udp, args.udp_args) if args.udp > 0 else None
        chaos = None
        if args.chaos:
            from ..chaos import ChaosSchedule

            chaos = ChaosSchedule(
                args.chaos, n, nb_real_byz=r, args=args.chaos_args,
                allow_topology_faults=args.topology is not None,
            )
            info("Chaos schedule: %d regime(s): %s" % (
                len(chaos), "  ".join("%d:%s" % t for t in chaos.transitions())
            ))
            if topology_spec is not None:
                # every corrupt-agg/straggle-agg target must name a node
                # the declared tree actually has — rejected here, loudly,
                # before any compilation
                for regime in chaos.regimes:
                    for lvl, unit in regime.agg_corrupt + regime.agg_straggle:
                        topology_spec.validate_fault_target(lvl, unit)

        base_schedule = build_schedule(args.learning_rate, args.learning_rate_args)

        # One-time validations and warnings — outside the (re)builder so an
        # escalation rebuild never repeats them.
        if mesh_axes is not None:
            if args.input_source == "device":
                raise UserException(
                    "--input-source device needs the flat engine (the sharded "
                    "engine's batches flow through the pipeline stages); drop "
                    "--mesh or use --input-source stream"
                )
            if not getattr(experiment, "supports_sharded", False):
                raise UserException(
                    "Experiment %r does not publish sharded hooks (sharded_init/"
                    "sharded_specs/sharded_loss); --mesh currently works with: %s"
                    % (args.experiment, ", ".join(
                        name for name in models.itemize()
                        if getattr(models.get(name), "supports_sharded", False)) or "none")
                )
            if args.leaf_bucketing != "auto":
                warning(
                    "--leaf-bucketing applies to the flat engine's leaf path "
                    "only; the sharded engine always aggregates per bucket"
                )
            if args.trace_ops:
                warning(
                    "--trace-ops narrates the flat engine's step body only; "
                    "ignored under --mesh (use --trace for a profiler window)"
                )
        else:
            if args.granularity in ("layer", "global"):
                raise UserException(
                    "--granularity %s needs the sharded engine: pass --mesh W,PP,TP"
                    % args.granularity
                )
            if args.leaf_bucketing != "auto" and args.granularity != "leaf":
                warning(
                    "--leaf-bucketing only affects --granularity leaf; ignored "
                    "for granularity %r" % args.granularity
                )
            if args.input_source == "device":
                if jax.process_count() > 1:
                    raise UserException(
                        "--input-source device is single-process for now: "
                        "replicating the dataset would device_put onto "
                        "non-addressable devices; use --input-source stream"
                    )
                if (experiment.train_arrays() is None
                        and experiment.route_augmentation_to_device()):
                    # host-tier augmentation with an in-step device twin
                    # (models/preprocessing.py): re-route it so augmented
                    # training gets device sampling too (the augmentation
                    # STREAM changes — in-step keyed draws — exactly like
                    # the sample stream device sampling already changes)
                    info(
                        "--input-source device: routing %r augmentation "
                        "through the in-step device tier"
                        % getattr(experiment, "preprocessing", "host")
                    )
                if experiment.train_arrays() is None:
                    raise UserException(
                        "--input-source device: experiment %r keeps a host-side "
                        "batch transform or a streaming corpus (train_arrays() "
                        "is None), so an in-graph gather cannot reproduce its "
                        "input stream; use --input-source stream" % args.experiment
                    )

        # Bounded-wait aggregation (--step-deadline, parallel/bounded.py):
        # per-worker async submissions against a host deadline; stalls
        # without a deadline drive the SYNCHRONOUS baseline the straggler
        # sweep compares against.  Validated before any compilation.
        straggler_model = None
        deadline_controller = None
        if bounded_wait:
            from ..parallel.bounded import BoundedWaitStep, HostStragglerModel

            # bounded-wait v3: nontrivial (pipe x model) submeshes are
            # supported — engine.build_submesh_grad compiles one collective
            # program per worker-axis submesh, so each of the W submissions
            # carries its own deadline (docs/engine.md, "v3: submesh
            # deadlines and age reweighting")
            if args.incremental_aggregation and mesh_axes is not None:
                raise UserException(
                    "--incremental-aggregation folds per-WORKER rows; the "
                    "sharded mode's per-submesh submissions need a "
                    "per-group fold layout — run the flat engine"
                )
            if args.incremental_aggregation and args.step_deadline is None:
                raise UserException(
                    "--incremental-aggregation overlaps decode with the "
                    "deadline window; pass --step-deadline"
                )
            if mesh_axes is not None and args.microbatches is not None:
                raise UserException(
                    "--step-deadline on the sharded engine computes per-"
                    "worker FULL-batch gradients over experiment.loss; "
                    "--microbatches only shapes the fused pipeline loss — "
                    "drop it (the bounded path would silently ignore it)"
                )
            if unroll > 1:
                raise UserException(
                    "--step-deadline closes every round on the host clock; "
                    "a scanned --unroll chunk cannot be interrupted — use "
                    "--unroll 1"
                )
            if args.input_source == "device":
                raise UserException(
                    "--step-deadline dispatches per-worker host batches; use "
                    "--input-source stream"
                )
            if args.secure_mask:
                raise UserException(
                    "--step-deadline + --secure-mask is not supported: the "
                    "pairwise pads are added inside the fused submission "
                    "pipeline and would not cancel across per-worker "
                    "dispatches (--secure digests DO ride the bounded path)"
                )
            if args.udp > 0:
                raise UserException(
                    "--step-deadline replaces the simulated lossy transport; "
                    "drop --UDP (real timeouts produce the NaN rows)"
                )
            if jax.process_count() > 1:
                raise UserException(
                    "--step-deadline is single-process (the submission "
                    "threads poll one host's device streams)"
                )
            # a schedule whose only content is topology faults belongs to
            # the TREE plane (topology.schedule above); the worker-plane
            # straggler model consumes straggler regimes and refuses
            # in-graph fault kinds — hand it the schedule only when there
            # is worker-plane content to consume or refuse
            chaos_worker = chaos
            if chaos is not None and not (
                    chaos.has_stragglers or chaos.has_attacks
                    or chaos.has_drop or chaos.has_forgery):
                chaos_worker = None
            if (args.straggler_stall > 0 or args.straggler_rate > 0
                    or chaos_worker is not None):
                straggler_model = HostStragglerModel(
                    n, args.straggler_stall, rate=args.straggler_rate,
                    chaos=chaos_worker, seed=args.seed,
                    jitter=args.straggler_jitter,
                )
            elif args.straggler_jitter > 0:
                raise UserException(
                    "--straggler-jitter scales an injected stall; without "
                    "--straggler-stall/--straggler-rate or a --chaos "
                    "straggler regime it injects nothing — drop it or add "
                    "a stall source"
                )
            if args.deadline_percentile is not None:
                from ..parallel.deadline import DeadlineController

                if args.step_deadline is None:
                    raise UserException(
                        "--deadline-percentile needs --step-deadline (the "
                        "controller's initial window and default ceiling)"
                    )
                # constructed ONCE, outside the guardian rebuild path: the
                # learned window is host policy state that must survive an
                # escalation (and its registry instruments register once)
                deadline_controller = DeadlineController(
                    args.step_deadline,
                    percentile=args.deadline_percentile,
                    floor=args.deadline_floor,
                    ceiling=args.deadline_ceiling,
                    ema=args.deadline_ema,
                    registry=registry,
                )
            if args.stale_infill and args.step_deadline is None:
                raise UserException(
                    "--stale-infill needs --step-deadline: the synchronous "
                    "protocol never times anyone out"
                )
            if args.stale_reweight and not args.stale_infill:
                raise UserException(
                    "--stale-reweight rescales STALE CARRY rows; without "
                    "--stale-infill every miss is a NaN drop and there is "
                    "nothing to reweight — pass --stale-infill"
                )
            if topology_spec is not None:
                if mesh_axes is not None:
                    raise UserException(
                        "--topology needs the flat engine: the tree's "
                        "custody plane signs the stacked per-worker wire "
                        "rows, which the sharded submesh submissions never "
                        "materialize — drop --mesh"
                    )
                if args.incremental_aggregation:
                    raise UserException(
                        "--topology and --incremental-aggregation are "
                        "mutually exclusive: the tree's custody plane "
                        "signs the stacked wire rows at the round "
                        "barrier, which the incremental fold never "
                        "materializes"
                    )
                from ..topology import TreeAggregator

                # constructed ONCE, outside the guardian rebuild path,
                # exactly like the deadline controller: the custody chain
                # head and the learned per-level windows are host protocol
                # state that must survive an escalation (per-level
                # controllers carry no registry instruments of their own —
                # the TreeAggregator's labeled counters are the metrics
                # surface, so they cannot collide with the leaf
                # controller's gauges)
                topology = TreeAggregator(
                    topology_spec, registry=registry,
                    session_secret=(args.session_secret.encode()
                                    if args.session_secret else None),
                    deadline=args.step_deadline,
                    deadline_opts=(dict(
                        percentile=args.deadline_percentile,
                        floor=args.deadline_floor,
                        ceiling=args.deadline_ceiling,
                        ema=args.deadline_ema,
                    ) if args.deadline_percentile is not None else None),
                )
                topology.schedule = chaos
        elif (args.deadline_percentile is not None or args.stale_infill
                or args.stale_reweight or args.straggler_jitter > 0
                or args.incremental_aggregation):
            raise UserException(
                "--deadline-percentile/--stale-infill/--stale-reweight/"
                "--straggler-jitter/--incremental-aggregation are "
                "bounded-wait options; pass --step-deadline (or "
                "--straggler-stall for the synchronous baseline)"
            )
        if (exchange_codec is not None and exchange_codec.uses_ef
                and jax.process_count() > 1):
            raise UserException(
                "--exchange %s is single-process: the error-feedback "
                "residual is a worker-sharded buffer the checkpoint path "
                "serializes (a multi-host device_get cannot see every "
                "shard) — drop :ef or run one process"
                % exchange_codec.spec()
            )

        def make_regularized_loss(base_loss, l1, l2):
            # l1/l2 regularization wraps the per-worker loss (reference:
            # graph.py:125-139) — the ONE wrapper shared by the flat
            # engine and the sharded bounded-wait submission body, so the
            # two arms cannot silently diverge
            def loss_fn(params, batch):
                loss = base_loss(params, batch)
                leaves = jax.tree_util.tree_leaves(params)
                if l1:
                    loss = loss + l1 * sum(jnp.sum(jnp.abs(p)) for p in leaves)
                if l2:
                    loss = loss + l2 * sum(jnp.sum(p * p) for p in leaves)
                return loss

            return loss_fn

        class TrainingStack:
            """The rebuildable half of the run: engine + jitted step/eval
            programs + optimizer, derived from an Overrides record.  A
            guardian escalation builds a new one; everything else (mesh,
            experiment, chaos schedule, cadences) is immutable."""

        # Bucket-level masking (secure/masking.py): the pad key material
        # derives from the session secret; spec feasibility (mean-inner
        # meta-GAR) is validated inside enable_masking at parse time — and
        # again on every guardian escalation rebuild, so a ladder rung that
        # swaps to an unmaskable rule is rejected, not silently unmasked.
        group_masking = None
        if args.secure_mask:
            from ..secure import GroupMasking

            group_masking = GroupMasking.from_secret(args.session_secret.encode())

        def build_training(ov):
            ts = TrainingStack()
            ts.overrides = ov
            gar = gars.instantiate(ov.gar_name, n, ov.f, list(ov.gar_args))
            if group_masking is not None:
                from ..secure import enable_masking

                enable_masking(gar, group_masking)
            if ov.lr_scale != 1.0:
                # escalation's lr damping composes with the named schedule
                def schedule(s, _base=base_schedule, _x=ov.lr_scale):
                    return _base(s) * _x
            else:
                schedule = base_schedule
            tx = build_optimizer(args.optimizer, schedule, args.optimizer_args)
            ts.gar, ts.schedule, ts.tx = gar, schedule, tx
            ts.device_dataset = None
            ts.sampled_tail = None
            ts.bounded_step = None
            if mesh_axes is not None:
                # ---- sharded mode of the ONE engine (per-layer GAR on
                # sharded grads; docs/engine.md) ----
                # ``vector`` (the flat default) means whole-vector selection,
                # which the sharded mode spells ``global`` (one global (n, n)
                # distance matrix accumulated across shards).
                gran = "global" if args.granularity == "vector" else args.granularity
                engine = RobustEngine(
                    mesh, gar, nb_workers=n, sharding="sharded",
                    nb_real_byz=r, attack=attack, lossy_link=lossy,
                    granularity=gran, exchange_dtype=args.exchange_dtype,
                    worker_momentum=args.worker_momentum,
                    worker_metrics=args.worker_metrics,
                    reputation_decay=ov.reputation_decay,
                    quarantine_threshold=ov.quarantine_threshold,
                    # The sharded loss is a LOCAL PARTIAL under shard_map, so
                    # the engine applies l1/l2 analytically on the completed
                    # gradients instead of wrapping the loss (docs/engine.md)
                    l1_regularize=args.l1_regularize,
                    l2_regularize=args.l2_regularize,
                    # under bounded-wait the straggler schedule moved to the
                    # HOST clock (straggler_model); in-graph chaos is off
                    chaos=None if bounded_wait else chaos,
                    secure=args.secure,
                    flight=flight_rec,
                )
                loss_fn = experiment.sharded_loss(
                    mesh_axes[1],
                    2 if args.microbatches is None else args.microbatches,
                )

                def make_fresh_state(seed=args.seed):
                    return engine.init_state(
                        experiment.sharded_init(mesh_axes[1]), experiment.sharded_specs(),
                        tx, seed=seed,
                    )

                state0 = make_fresh_state()
                if bounded_wait:
                    # the sharded bounded-wait variant: per-submesh
                    # submission streams, per-group deadlines — on a
                    # nontrivial (pipe x model) mesh each unit is one
                    # collective program with its own window (v3,
                    # engine.build_submesh_grad).  The submission body
                    # needs the GLOBAL per-worker loss — the plain loss IS
                    # the local partial (GSPMD partitions it over the
                    # in-group axes), with l1/l2 folded in like the flat
                    # branch (the sharded engine's analytic reg path
                    # belongs to the fused step body).
                    bounded_loss = make_regularized_loss(
                        experiment.loss, args.l1_regularize, args.l2_regularize)

                    ts.bounded_step = BoundedWaitStep(
                        engine, bounded_loss, tx, state0.params,
                        deadline=args.step_deadline,
                        straggler_model=straggler_model, registry=registry,
                        controller=deadline_controller,
                        stale_infill=args.stale_infill,
                        stale_max_age=args.stale_max_age,
                        stale_reweight=args.stale_reweight,
                    )
                    ts.step_fn = ts.bounded_step
                else:
                    ts.step_fn = engine.build_step(loss_fn, tx, state0)
                ts.multi_fn = (
                    engine.build_multi_step(loss_fn, tx, state0) if unroll > 1 else None
                )
                ts.eval_fn = None  # metric sums need a dense replica; eval reports loss
                ts.eval_loss_fn = engine.build_eval(loss_fn, state0)
            else:
                engine = RobustEngine(
                    mesh, gar, n, nb_real_byz=r, attack=attack, lossy_link=lossy,
                    exchange_dtype=args.exchange_dtype, exchange=exchange_codec,
                    worker_momentum=args.worker_momentum,
                    batch_transform=experiment.device_transform(),
                    worker_metrics=args.worker_metrics,
                    reputation_decay=ov.reputation_decay,
                    quarantine_threshold=ov.quarantine_threshold,
                    granularity=args.granularity,
                    leaf_bucketing={"auto": "auto", "on": True, "off": False}[args.leaf_bucketing],
                    trace_ops=args.trace_ops,
                    # under bounded-wait the straggler schedule moved to the
                    # HOST clock (straggler_model); in-graph chaos is off
                    chaos=None if bounded_wait else chaos,
                    secure=args.secure,
                    flight=flight_rec,
                )

                loss_fn = make_regularized_loss(
                    experiment.loss, args.l1_regularize, args.l2_regularize)

                def make_fresh_state(seed=args.seed):
                    # params ALWAYS init from the run seed; ``seed`` only moves
                    # the RNG stream (guardian's from-scratch retry path)
                    return engine.init_state(
                        experiment.init(jax.random.PRNGKey(args.seed)), tx, seed=seed
                    )

                state0 = make_fresh_state()
                if bounded_wait:
                    # per-worker async submissions + deadline-closed rounds
                    # (the guardian rebuild path constructs this exactly
                    # like the fused step: one stack, one engine; the
                    # deadline CONTROLLER is shared across rebuilds — its
                    # learned window survives an escalation)
                    ts.bounded_step = BoundedWaitStep(
                        engine, loss_fn, tx, state0.params,
                        deadline=args.step_deadline,
                        straggler_model=straggler_model, registry=registry,
                        controller=deadline_controller,
                        stale_infill=args.stale_infill,
                        stale_max_age=args.stale_max_age,
                        stale_reweight=args.stale_reweight,
                        incremental=args.incremental_aggregation,
                        # the tree rides only its own rung: an escalation
                        # that swaps the rule retires the host plane with
                        # it (nothing to supervise under a flat rule)
                        topology=(topology if topology is not None
                                  and ov.gar_name == args.topology else None),
                    )
                    ts.step_fn = ts.bounded_step
                else:
                    ts.step_fn = engine.build_step(loss_fn, tx)
                if args.input_source == "device":
                    # The whole train split lives on the accelerator; the
                    # unrolled branch dispatches the in-graph sampling trainer
                    # (one scan per chunk, zero per-step host transfer).
                    ts.device_dataset = engine.replicate(experiment.train_arrays())
                    ts.multi_fn = engine.build_sampled_multi_step(
                        loss_fn, tx, repeat_steps=unroll,
                        batch_size=experiment.batch_size,
                    )
                    tail_fns = {}

                    def sampled_tail(nb_steps, _cache=tail_fns):
                        # The final (max_step - offstep) % unroll steps run
                        # device-sampled too, through ONE tail-sized
                        # executable (the remainder is invariant across the
                        # run — chunks advance by unroll and rollbacks land
                        # on chunk boundaries — so this compiles once; a
                        # compile-count test asserts it).
                        fn = _cache.get(nb_steps)
                        if fn is None:
                            fn = engine.build_sampled_multi_step(
                                loss_fn, tx, repeat_steps=nb_steps,
                                batch_size=experiment.batch_size,
                            )
                            _cache[nb_steps] = fn
                        return fn

                    ts.sampled_tail = sampled_tail
                else:
                    ts.multi_fn = engine.build_multi_step(loss_fn, tx) if unroll > 1 else None
                ts.eval_fn = engine.build_eval_sums(experiment.metrics)
                ts.eval_loss_fn = None
            ts.engine = engine
            ts.make_fresh_state = make_fresh_state
            ts.initial_state = state0
            # --gar-probe instrument (built lazily at the first summary fire
            # so unprobed runs pay nothing): the rule's wall time at the
            # run's exact (n, d), d = the whole model dimension.
            ts.model_dim = sum(
                int(np.prod(leaf.shape))
                for leaf in jax.tree_util.tree_leaves(state0.params)
            )
            ts.gar_probe_fn = None
            return ts

        ts = build_training(overrides)
        state = ts.initial_state

    # Cadences with config.py defaults (reference: config.py:54-61)
    def pick(value, default):
        return default if value is None else value

    # Multi-host discipline: evaluation is a *collective* (every process runs
    # the SPMD eval program), so its firing must be step-deterministic —
    # wall-clock cadences can disagree across hosts and deadlock the
    # collective.  File/snapshot writes are process-0-only (the reference has
    # exactly one evaluator and one PS writing state, runner.py:318-330).
    nb_processes = jax.process_count()
    lead = jax.process_index() == 0
    eval_period = pick(args.evaluation_period, config.default_evaluation_period)
    eval_delta = pick(args.evaluation_delta, config.default_evaluation_delta)
    if nb_processes > 1 and eval_period >= 0.0:
        if eval_delta < 0:
            warning(
                "Multi-process run: wall-period eval is not host-deterministic and "
                "is DISABLED; pass --evaluation-delta to evaluate"
            )
        else:
            warning("Multi-process run: ignoring --evaluation-period (keeping the step delta)")
        eval_period = -1.0

    eval_trigger = CadenceTrigger(eval_delta, eval_period)
    ckpt_trigger = CadenceTrigger(
        pick(args.checkpoint_delta, config.default_checkpoint_delta),
        pick(args.checkpoint_period, config.default_checkpoint_period),
    )
    summary_trigger = CadenceTrigger(
        pick(args.summary_delta, config.default_summary_delta),
        pick(args.summary_period, config.default_summary_period),
    )
    ckpt_auth = None
    ckpt_cipher = None
    if args.encrypt_checkpoints and not args.session_secret:
        raise UserException(
            "--encrypt-checkpoints derives its key from --session-secret; "
            "pass both"
        )
    if args.session_secret and args.checkpoint_dir:
        # The session secret also tags snapshots: a swapped/corrupted
        # checkpoint fails verification at restore instead of silently
        # seeding training (reference parity: the same key material signs
        # gradients and would sign any persisted state).
        from ..parallel.auth import GradientAuthenticator

        # context=b"ckpt" keeps checkpoint-tag keys disjoint from the
        # bring-up handshake's (same secret, separate key family)
        ckpt_auth = GradientAuthenticator(args.session_secret.encode(), 1, context=b"ckpt")
        if args.encrypt_checkpoints:
            from ..parallel.crypto import SnapshotCipher

            ckpt_cipher = SnapshotCipher(args.session_secret.encode())
    # Authenticated gradient submission (secure/submit.py): the host-side
    # aggregator role — per-(worker, step) HMAC sign/verify over the
    # in-graph digests, fed one dispatch behind like the forensics ledger.
    # Lead-only: the digests are replicated, every process would verify
    # identical material.
    secure_auth = None
    if args.secure and lead:
        from ..secure import SubmissionAuthenticator

        secure_auth = SubmissionAuthenticator(
            args.session_secret.encode(), n, registry=registry
        )
    # Chain of custody (secure/custody.py): signed lineage manifests beside
    # every snapshot, verified by this runner's auto-restore and the
    # guardian rollback restore — the training end of train -> sign -> serve.
    custody = None
    if args.secure and args.checkpoint_dir:
        from ..secure import ChainOfCustody
        from ..secure.custody import data_digest_for

        identity = "%s|%s|seed=%d|n=%d" % (
            args.experiment, " ".join(args.experiment_args), args.seed, n,
        )
        custody = ChainOfCustody(
            args.session_secret.encode(), run_id=run_id,
            experiment=args.experiment,
            gar_spec=overrides.describe(),
            data_digest=data_digest_for(experiment, identity),
            submission=secure_auth,
            allow_unsigned=args.allow_unsigned,
        )
    checkpoints = Checkpoints(
        args.checkpoint_dir,
        pick(args.checkpoint_base_name, config.default_checkpoint_base_name),
        args.checkpoint_keep,
        authenticator=ckpt_auth,
        cipher=ckpt_cipher,
        custody=custody,
        allow_legacy_tags=not args.no_legacy_checkpoint_tags,
        # Serialization + disk I/O run on a writer thread (the host fetch
        # stays synchronous — the step donates the state buffers); wait()
        # joins at every later fire and at exit, so a failing write surfaces
        # within one cadence and a returned run is fully flushed.
        background=True,
    ) if args.checkpoint_dir else None
    save_snapshots = checkpoints is not None and lead
    eval_file = EvalFile(args.evaluation_file if lead else None)
    summaries = SummaryWriter(args.summary_dir if lead else None, run_id=run_id)

    # Byzantine forensics ledger (obs/forensics.py): fed one dispatch behind
    # (the same lag as the NaN-abort check, so the feed never blocks the
    # in-flight step), written at exit.  Lead-only — the diagnostics are
    # replicated, every process would ledger identical evidence.
    ledger = None
    if args.forensics and lead:
        ledger = ForensicsLedger(n, run_id=run_id)
    if topology is not None and ledger is not None:
        # the tree's custody verdicts land on the run ledger's SEPARATE
        # sub-aggregator surface (obs/forensics.py) — a forged emission
        # names its (level, unit), never a worker
        topology.ledger = ledger

    # Compile observability (obs/profiler.py): every compile-cache miss of
    # a wrapped executable becomes a named counter + a tagged summary event
    # carrying the offending abstract shapes; jax.monitoring additionally
    # counts every backend compile in the process.  Host-side polling only
    # — the jitted programs are never touched.
    compile_watch = obs_profiler.CompileWatch(
        # ``step`` is the train loop's local below; the provider only runs
        # when a wrapped dispatch fires, by which point it is assigned
        registry, summaries=summaries, step_provider=lambda: step
    )
    obs_profiler.install_compile_listener(registry)
    nb_mem_devices = obs_profiler.install_memory_gauges(registry)
    if nb_mem_devices:
        info("Device memory gauges live on %d device(s)" % nb_mem_devices)

    def instrument_stack(stack):
        """Wrap a TrainingStack's dispatches in the compile watch (called
        on the initial stack and on every guardian escalation rebuild)."""
        stack.step_fn = compile_watch.wrap("train_step", stack.step_fn)
        if stack.multi_fn is not None:
            stack.multi_fn = compile_watch.wrap("train_multi_step", stack.multi_fn)
        if stack.eval_fn is not None:
            stack.eval_fn = compile_watch.wrap("eval_step", stack.eval_fn)
        if stack.eval_loss_fn is not None:
            stack.eval_loss_fn = compile_watch.wrap("eval_loss", stack.eval_loss_fn)
        if stack.sampled_tail is not None:
            inner_tail = stack.sampled_tail
            stack.sampled_tail = lambda nb: compile_watch.wrap(
                "train_sampled_tail[%d]" % nb, inner_tail(nb)
            )
        return stack

    instrument_stack(ts)

    def dump_metrics_file():
        if not args.metrics_file or not lead:
            return
        tmp = args.metrics_file + ".tmp"
        with open(tmp, "w") as fd:
            fd.write(registry.render_prometheus())
        os.replace(tmp, args.metrics_file)

    # Auto-restore the latest checkpoint (reference: runner.py:514-525).
    # Every process must make the SAME restore decision or the SPMD step
    # counts diverge and the collectives deadlock, so process 0's choice is
    # broadcast and the others must be able to see that snapshot (shared
    # filesystem) — failing loudly beats hanging.
    offstep = 0
    if checkpoints is not None:
        steps_on_disk = checkpoints.steps()
        target_step = steps_on_disk[-1] if steps_on_disk else -1
        if nb_processes > 1:
            from jax.experimental import multihost_utils

            target_step = int(multihost_utils.broadcast_one_to_all(np.int32(target_step)))
            if target_step >= 0 and not checkpoints.can_restore(target_step):
                raise UserException(
                    "Process %d cannot see checkpoint step %d: multi-host resume needs "
                    "--checkpoint-dir on a filesystem shared with process 0"
                    % (jax.process_index(), target_step)
                )
        if target_step >= 0:
            with Context("restore"):
                # The worker-sharded side buffers (CLEVER carry, momentum) may
                # span hosts and are never serialized: keep the live zeroed
                # buffers aside and restore into a stripped host template.
                carry, momentum = state.carry, state.momentum
                template = jax.device_get(state.replace(carry=None, momentum=None))
                restored, offstep = checkpoints.restore(template, step=target_step)
                state = ts.engine.put_state(restored.replace(carry=carry, momentum=momentum))
            if lead:
                # Rows beyond the restored step belong to a timeline this
                # run is about to overwrite; appending after them would
                # leave duplicate/interleaved step columns in the TSV.
                dropped = eval_file.truncate_after(offstep)
                if dropped:
                    info(
                        "Trimmed %d stale eval row(s) beyond restored step %d"
                        % (dropped, offstep)
                    )
            if watchdog is not None and offstep > 0:
                # The snapshot this run just trusted enough to resume FROM is
                # the guardian's initial last-known-good: a divergence before
                # the first healthy in-run save must roll back here, not wipe
                # the directory and restart from scratch.
                checkpoints.pin(offstep)

    # Multi-host boundary authentication (reference parity: every worker->PS
    # push is signed, mpi_rendezvous_mgr.patch:585-627; here the surface is
    # process bring-up — see parallel/auth.py docstring). After restore, so
    # the digest covers the parameters training will actually start from.
    if args.session_secret:
        from ..parallel.auth import authenticate_processes

        with Context("auth"):
            authenticate_processes(
                args.session_secret.encode(), state.params, step=offstep,
                verify_equal=mesh_axes is None,
            )
            info("Host handshake OK: %d process(es) authenticated" % nb_processes)
    elif nb_processes > 1:
        warning(
            "Multi-process run without --session-secret: the host boundary is "
            "UNAUTHENTICATED (the reference signs every worker->PS tensor, "
            "mpi_rendezvous_mgr.patch:585-627); pass the same --session-secret "
            "on every host to enable the bring-up handshake"
        )

    max_step = pick(args.max_step, config.default_max_step)
    train_iter = None
    prefetcher = None
    chunk_pipeline = None

    def next_chunk():
        """K distinct batches as one (K, n, ...) stack for the unrolled path
        (one contiguous gather via next_many when the iterator provides it)."""
        if hasattr(train_iter, "next_many"):
            return train_iter.next_many(unroll)
        return jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *[next(train_iter) for _ in range(unroll)]
        )

    def reset_input(start_step, reseed=0):
        """(Re)build the input pipeline positioned at ``start_step``.

        Called at startup (start_step = the auto-restored step) and after a
        guardian rollback.  The stream is FAST-FORWARDED to ``start_step``
        so a resumed run consumes exactly the batches the uninterrupted run
        would have — the last piece of bit-identical resume (the serialized
        step/params/opt-state/RNG already restore exactly).  A rollback
        passes ``reseed`` > 0 instead: it draws the replay window's batches
        from a fresh stream, one more way a retry differs from the
        deterministic trajectory that just diverged."""
        nonlocal train_iter, prefetcher, chunk_pipeline
        if prefetcher is not None:
            prefetcher.close()
            prefetcher = None
        if chunk_pipeline is not None:
            chunk_pipeline.close()
            chunk_pipeline = None
        train_iter = experiment.make_train_iterator(
            n, seed=args.seed + 1 + RESEED_STRIDE * reseed
        )
        if start_step and not reseed:
            if hasattr(train_iter, "skip"):
                train_iter.skip(start_step)
            else:
                if start_step > 1000:
                    warning(
                        "Resume fast-forward: this iterator has no skip(), so "
                        "%d batches are drawn and discarded to realign the "
                        "sample stream — expect a slow startup" % start_step
                    )
                for _ in range(start_step):
                    next(train_iter)
        if args.prefetch > 0 and nb_processes == 1 and ts.device_dataset is None:
            # Overlap host batch assembly + host->device transfer with compute
            # (the reference's fetcher/batcher threads + prefetch queue,
            # cnnet.py:115-146).  Disabled in multi-process runs: a background
            # device_put would interleave differently on each host, breaking the
            # strict cross-process ordering collectives require.
            from ..models.datasets import (
                ChunkPipeline, DevicePrefetcher, supports_buffered_next_many)

            if unroll == 1:
                prefetcher = DevicePrefetcher(
                    train_iter, ts.engine.shard_batch, depth=args.prefetch
                )
            elif not args.trace:
                # The three-stage chunk pipeline (docs/input_pipeline.md):
                # parallel sharded gather into ping-pong host buffers,
                # sliced async transfer, device-side assemble — overlap is
                # exported through the metrics registry (input_* family).
                # FINITE producer: exactly the chunks the loop will consume
                # ((max_step-start_step) // unroll — the loop's unrolled-branch
                # count is deterministic).  An infinite producer would over-draw
                # from the shared train_iter and the tail handoff would discard
                # a thread-timing-dependent number of draws, skipping the tail's
                # sample stream ahead nondeterministically.  By the time the
                # per-step tail starts, all chunks were consumed, so the
                # producer has exhausted its iterator and exited — the tail's
                # direct train_iter use cannot race the daemon.  (--trace runs
                # interleave per-step and unrolled dispatches, breaking the
                # chunk count: they keep the synchronous path.)
                chunks_total = max(0, (max_step - start_step)) // unroll
                if chunks_total > 0 and supports_buffered_next_many(train_iter):
                    chunk_pipeline = ChunkPipeline(
                        train_iter, unroll, chunks_total,
                        put=ts.engine.shard_batches,
                        assemble=ts.engine.assemble_batches,
                        depth=args.prefetch, slices=args.input_slices,
                        registry=registry,
                    )
                elif chunks_total > 0:
                    # iterators without a buffered next_many(k, out=...)
                    # (plugin experiments, possibly on the pre-pipeline
                    # signature) keep the legacy whole-chunk prefetch thread

                    def chunk_source():
                        for _ in range(chunks_total):
                            yield next_chunk()

                    chunk_pipeline = DevicePrefetcher(
                        chunk_source(), ts.engine.shard_batches, depth=args.prefetch
                    )

    reset_input(offstep)

    def fold_metric_sums(sums, folded):
        """Accumulate one batch's (total, count) metric sums."""
        if sums is None:
            return folded
        return jax.tree_util.tree_map(lambda a, b: a + b, sums, folded)

    def normalize_metric_sums(sums):
        return {name: float(total) / max(float(count), 1.0) for name, (total, count) in sums.items()}

    dense_metrics_fn = None
    if ts.eval_fn is None and nb_processes == 1 and hasattr(experiment, "sharded_to_dense_params"):
        # Jitted once; the dense replica's params live on device between
        # eval batches instead of re-uploading per batch.
        dense_metrics_fn = jax.jit(experiment.metrics)

    @trace.span("eval", cat="eval")
    def run_eval(step):
        if ts.eval_fn is None:
            # Sharded engine: the sharded loss is always reported; when the
            # experiment can collapse its stage-stacked params to the dense
            # layout (and this is a single process that can see every
            # shard), a dense replica also reports the real metric dict
            # (accuracy/nll — the reference's evaluation contract).
            values, sums = [], None
            dense_params = None
            if dense_metrics_fn is not None:
                dense_params = jax.device_put(
                    experiment.sharded_to_dense_params(jax.device_get(state.params))
                )
            for batch in experiment.make_eval_iterator(n):
                values.append(
                    float(jax.device_get(ts.eval_loss_fn(state, ts.engine.shard_batch(batch))))
                )
                if dense_params is not None:
                    flat = jax.tree_util.tree_map(
                        lambda x: x.reshape((-1,) + x.shape[2:]), batch
                    )  # fold the worker dim: the dense replica sees one big batch
                    sums = fold_metric_sums(
                        sums, jax.device_get(dense_metrics_fn(dense_params, flat))
                    )
            metrics = {"loss": sum(values) / max(len(values), 1)}
            if sums is not None:
                metrics.update(normalize_metric_sums(sums))
        else:
            sums = None
            for batch in experiment.make_eval_iterator(n):
                sums = fold_metric_sums(
                    sums, jax.device_get(ts.eval_fn(state, ts.engine.shard_batch(batch)))
                )
            metrics = normalize_metric_sums(sums)
        if chaos is not None:
            # the regime column: the regime that governed the LAST COMPLETED
            # training step (``step`` counts completed steps, so the final
            # step's in-graph index is step - 1 — an eval landing exactly on
            # a switch step reports the regime its metrics were trained
            # under, not the one about to start)
            metrics["chaos_regime"] = chaos.regime_at(max(step - 1, 0))
        info("Evaluation at step %d: %s" % (step, "  ".join("%s=%.4f" % kv for kv in sorted(metrics.items()))))
        eval_file.append(step, metrics)
        return metrics

    perf = PerfReport(registry=registry)
    # Live view shared by the exporter's /status and the flight fetches —
    # plain dict writes under the GIL; scrape threads only read.
    live_state = {"step": offstep, "flight": None, "slo": None}
    live = None
    if args.live_port is not None and lead:

        def live_status():
            return {
                "step": live_state["step"],
                "max_step": max_step,
                "steps_per_s": perf.steps_per_s_excl_first(),
                "overrides": overrides.describe(),
                "flight": live_state["flight"],
                "slo": live_state["slo"],
            }

        live = obs_live.LiveExporter(
            registry=registry, status_provider=live_status, run_id=run_id,
            host=args.live_host, port=args.live_port,
        )
        live_addr = live.serve_background()
        if args.live_ready_file:
            # atomic publish, like serve's --ready-file handshake
            ready_dir = os.path.dirname(args.live_ready_file)
            if ready_dir:
                os.makedirs(ready_dir, exist_ok=True)
            tmp = args.live_ready_file + ".tmp"
            with open(tmp, "w") as fd:
                fd.write("%s %d\n" % live_addr)
            os.replace(tmp, args.live_ready_file)
    # Training gauges on the process-wide registry (obs/metrics.py): the
    # same values the summary stream carries, updated at every summary fire
    # and dumped as Prometheus text by --metrics-file.
    g_loss = registry.gauge("train_loss", "Last summarized total training loss")
    g_grad_norm = registry.gauge("train_grad_norm", "Last summarized aggregate norm")
    g_lr = registry.gauge("train_learning_rate", "Learning rate at the last summary")
    g_steps_per_s = registry.gauge(
        "train_steps_per_second", "Throughput excluding the first (compile) step"
    )
    g_regime = registry.gauge("train_chaos_regime", "Active chaos regime index")
    g_quarantined = registry.gauge("train_quarantined_workers", "Workers under quarantine")
    g_worker_dist = registry.gauge(
        "train_worker_sq_dist", "Per-worker squared distance to the aggregate",
        labelnames=("worker",),
    )
    g_worker_rep = registry.gauge(
        "train_worker_reputation", "Per-worker reputation EMA (1 = trusted)",
        labelnames=("worker",),
    )
    # GAR cost instrumentation (--gar-probe, docs/gar_scaling.md): wall time
    # of ONE rule application at the run's exact (n, d), measured on a jitted
    # rule-only executable so the composite-vs-flat scaling claim is checked
    # against the live run, not just the offline benchmark.
    c_gar_seconds = registry.counter(
        "gar_seconds_total", "Cumulative measured GAR aggregation wall time"
    )
    g_gar_probe = registry.gauge(
        "gar_probe_seconds", "Last measured single-aggregation GAR wall time"
    )
    # Wire accounting (parallel/compress.py, docs/engine.md "The wire"):
    # bytes of the (n, d) submission stack per step under the configured
    # exchange — a static function of the run's geometry, counted per
    # dispatched step so the compression win is a number, not a claim.
    # Constant across guardian rebuilds (the ladder never changes d or the
    # exchange), so computed once here.
    c_wire_bytes = registry.counter(
        "bytes_on_wire_total",
        "Gradient-exchange submission bytes shipped over the wire",
    )
    g_wire_ratio = registry.gauge(
        "exchange_compression_ratio",
        "f32-wire bytes over configured-exchange bytes (>= 1)",
    )
    wire_step_bytes = n * compress.bytes_per_row(
        ts.model_dim, dtype=ts.engine.exchange_dtype, codec=ts.engine.codec
    )
    g_wire_ratio.set(compress.compression_ratio(
        ts.model_dim, dtype=ts.engine.exchange_dtype, codec=ts.engine.codec
    ))
    # guardian recovery counters — the third subsystem on the one registry
    g_rollbacks = registry.counter(
        "guardian_rollbacks_total", "Guardian rollbacks to last-known-good"
    )
    g_escalations = registry.counter(
        "guardian_escalations_total", "Guardian escalation-ladder rungs applied"
    )
    g_recoveries = registry.counter(
        "guardian_recoveries_total", "Guardian diverged-then-recovered verdicts"
    )
    # flight-recorder fetch accounting (obs/flight.py): one amortized host
    # copy per summary fire instead of per-dispatch pulls
    c_flight_fetches = registry.counter(
        "flight_fetches_total", "Flight-recorder ring fetches"
    )
    g_flight_rows = registry.gauge(
        "flight_window_steps", "Rows in the last fetched flight window"
    )
    g_flight_last = registry.gauge(
        "flight_last_step", "Completed step of the newest fetched flight row"
    )
    metrics = {}
    diverged = False
    with Context("train"):
        step = offstep
        trace_ctx = None
        # NaN divergence is checked with a ONE-STEP LAG: blocking on the
        # current step's loss every iteration would serialize host and device
        # and defeat async dispatch; checking the previous step's (by now
        # materialized) loss keeps one step in flight with the same abort
        # guarantee one step later (the reference checks synchronously only
        # because sess.run already blocked, runner.py:570-574).  The guardian
        # watchdog rides the same lag: ``pending_metrics`` keeps the whole
        # previous dispatch so the probe can be observed per sub-step.
        pending_loss = None
        pending_metrics = None
        pending_start = 0

        def time_gar_probe(step):
            """One timed GAR-only aggregation (--gar-probe): the executable
            is built and warmed at the first fire (compile excluded from the
            timing — it is a separate jit cache, so the TRAINING step's
            compile count is untouched), then each fire measures one
            blocked-on aggregation and feeds the registry."""
            from aggregathor_tpu.gars.scaling import sync_fetch

            if ts.gar_probe_fn is None:
                with trace.span("gar.probe_build", cat="train"):
                    ts.gar_probe_fn = ts.engine.build_gar_probe(ts.model_dim)
                    sync_fetch(ts.gar_probe_fn(0))  # compile + full drain
            with trace.span("gar.aggregate", cat="train"):
                begin = time.perf_counter()
                sync_fetch(ts.gar_probe_fn(step))
                elapsed = time.perf_counter() - begin
            c_gar_seconds.inc(elapsed)
            g_gar_probe.set(elapsed)
            return elapsed

        def summary_scalars(step, metrics):
            """The summary event payload — shared by the cadence fires and
            the final fire, so worker diagnostics never silently drop out of
            the last event."""
            scalars = {
                "total_loss": float(jax.device_get(metrics["total_loss"])),
                "grad_norm": float(jax.device_get(metrics["grad_norm"])),
                "learning_rate": float(ts.schedule(step)),
                "steps_per_s": perf.steps_per_s_excl_first(),
            }
            if "worker_sq_dist" in metrics:
                wd = np.asarray(jax.device_get(metrics["worker_sq_dist"]))
                scalars["worker_sq_dist"] = wd
                # Masked rows (lossy NaN infill, quarantine) carry non-finite
                # distance sums; np.argmax would return the FIRST such index,
                # flagging a masked worker instead of the most distant live
                # one. Masked workers are already surfaced via
                # nb_quarantined/participation — suspicion ranks the live set.
                # With NO finite entry (every row masked) there is no live set
                # to rank — argmax over all -inf would arbitrarily flag worker
                # 0, so the field is omitted instead.
                if np.any(np.isfinite(wd)):
                    scalars["suspect_worker"] = int(
                        np.argmax(np.where(np.isfinite(wd), wd, -np.inf))
                    )
            if "worker_participation" in metrics:
                scalars["worker_participation"] = np.asarray(
                    jax.device_get(metrics["worker_participation"])
                )
            if "worker_reputation" in metrics:
                scalars["worker_reputation"] = np.asarray(
                    jax.device_get(metrics["worker_reputation"])
                )
            if "nb_quarantined" in metrics:
                scalars["nb_quarantined"] = int(jax.device_get(metrics["nb_quarantined"]))
            if "chaos_regime" in metrics:
                scalars["chaos_regime"] = int(jax.device_get(metrics["chaos_regime"]))
            if "nb_timeouts" in metrics:
                # bounded-wait deadline verdicts for this dispatch's step
                scalars["straggler_timeouts"] = int(jax.device_get(metrics["nb_timeouts"]))
            if "nb_stale" in metrics:
                scalars["stale_infill_rows"] = int(jax.device_get(metrics["nb_stale"]))
            if ts.bounded_step is not None and ts.bounded_step.controller is not None:
                scalars["deadline_window_seconds"] = (
                    ts.bounded_step.controller.window
                )
            if args.gar_probe:
                scalars["gar_seconds"] = time_gar_probe(step)
            if flight_rec is not None:
                # ONE amortized ring fetch per summary fire: the last
                # dispatch already materialized the state, so this is a
                # host copy, not a device sync (the recorder's whole
                # host-side cost).
                with trace.span("flight.fetch", cat="obs"):
                    window = flight_rec.fetch(state.flight)
                c_flight_fetches.inc()
                nb_rows = int(window["step"].size)
                g_flight_rows.set(nb_rows)
                if nb_rows:
                    g_flight_last.set(int(window["step"][-1]) + 1)
                live_state["flight"] = obs_flight.summarize_window(window)
                scalars["flight_rows"] = nb_rows
            # mirror into the registry — one metrics surface (obs/metrics.py)
            g_loss.set(scalars["total_loss"])
            g_grad_norm.set(scalars["grad_norm"])
            g_lr.set(scalars["learning_rate"])
            g_steps_per_s.set(scalars["steps_per_s"])
            if "chaos_regime" in scalars:
                g_regime.set(scalars["chaos_regime"])
            if "nb_quarantined" in scalars:
                g_quarantined.set(scalars["nb_quarantined"])
            if "worker_sq_dist" in scalars:
                for w, value in enumerate(scalars["worker_sq_dist"]):
                    g_worker_dist.labels(worker=str(w)).set(
                        float(value) if np.isfinite(value) else float("inf")
                    )
            if "worker_reputation" in scalars:
                for w, value in enumerate(scalars["worker_reputation"]):
                    g_worker_rep.labels(worker=str(w)).set(float(value))
            return scalars

        def check_divergence():
            nonlocal diverged
            # ``pending_loss`` is the full per-step loss vector when unrolled,
            # so a mid-chunk divergence is caught at the next chunk boundary
            # rather than up to 2K-1 steps late via the last element only.
            if pending_loss is None:
                return
            with trace.span("block.loss_fetch", cat="train"):
                values = np.asarray(jax.device_get(pending_loss))
            if not np.all(np.isfinite(values)):
                if watchdog is not None:
                    return  # the guardian owns divergence: rollback, not abort
                diverged = True
                raise UserException("Training diverged (non-finite loss around step %d)" % step)

        def flight_postmortem(reason, at_step):
            """Fetch + dump the in-scan ring: exact per-step evidence for
            the window that killed the run (obs/flight.py), attached to the
            forensics report.  Called on guardian rollback and on
            crash/divergence, BEFORE the state is discarded."""
            if flight_rec is None:
                return None
            try:
                window = flight_rec.fetch(state.flight)
            except Exception as exc:
                warning("flight: post-mortem fetch failed: %s" % exc)
                return None
            summary = obs_flight.summarize_window(window)
            path = None
            if args.flight_dump and lead:
                path = args.flight_dump
                if reason == "guardian_rollback":
                    # every rollback keeps its own dump; the final
                    # crash/divergence dump owns the bare path
                    root, ext = os.path.splitext(path)
                    path = "%s.rollback-%d%s" % (root, int(at_step), ext or ".json")
                obs_flight.dump_window(
                    path, window, run_id=run_id, reason=reason,
                    capacity=flight_rec.capacity,
                    extra={"at_step": int(at_step)},
                )
                info("Flight post-mortem (%s) -> %r (%d row(s))"
                     % (reason, path, summary.get("rows", 0)))
            if ledger is not None:
                ledger.attach_flight(at_step, reason, path=path,
                                     window_summary=summary)
            # journal cross-ref: the event points at the dump that holds
            # the per-step evidence (one file -> the other)
            obs_events.emit("flight_postmortem", step=at_step, reason=reason,
                            path=path, rows=summary.get("rows", 0))
            return path

        # Secure submission feed (secure/submit.py): the host-side HMAC
        # sign/verify over the previous dispatch's digests — the same
        # one-dispatch lag as the forensics feed, so the crypto never blocks
        # the in-flight step.  Verdicts are keyed by step for the forensics
        # feed to attach as named ``forgery`` evidence.
        secure_fed = {"start": None}
        secure_verdicts = {}

        def feed_pending_secure():
            if secure_auth is None or pending_metrics is None:
                return
            if "secure" not in pending_metrics:
                return
            if secure_fed["start"] == pending_start:
                return
            secure_fed["start"] = pending_start
            with trace.span("secure.verify", cat="obs"):
                sec = {
                    name: np.asarray(jax.device_get(value))
                    for name, value in pending_metrics["secure"].items()
                }
                sent, recv = sec["digest_sent"], sec["digest_recv"]
                forged, rejected = sec["forged"], sec["rejected"]
                if sent.ndim == 2:  # single step -> one-step chunk
                    sent, recv = sent[None], recv[None]
                    forged, rejected = forged[None], rejected[None]
                for i in range(sent.shape[0]):
                    at_step = pending_start + i + 1
                    ok = secure_auth.process_step(
                        at_step, sent[i], recv[i], forged=forged[i]
                    )
                    if not np.array_equal(~ok, rejected[i].astype(bool)):
                        # cannot happen by construction (the in-graph
                        # rejection models exactly the tag-verification
                        # outcome) — if it does, the simulation drifted
                        warning(
                            "secure: host verification disagrees with the "
                            "in-graph rejection at step %d" % at_step
                        )
                    if ledger is not None:
                        secure_verdicts[at_step] = ~ok

        # Forensics feed: one ledger observation per completed step, taken
        # from the PREVIOUS dispatch (the same one-step lag as the NaN-abort
        # check — by feed time the values are materialized, so the fetch
        # costs a host copy, not a device sync).  ``fed_start`` dedups: the
        # same pending dispatch is visible from several call sites.
        forensics_fed = {"start": None}

        def feed_pending_forensics():
            if ledger is None or pending_metrics is None:
                return
            if forensics_fed["start"] == pending_start:
                return
            forensics_fed["start"] = pending_start
            with trace.span("forensics.feed", cat="obs"):
                def fetch(value):
                    return None if value is None else np.asarray(jax.device_get(value))

                dist = fetch(pending_metrics.get("worker_sq_dist"))
                rep = fetch(pending_metrics.get("worker_reputation"))
                regime = fetch(pending_metrics.get("chaos_regime"))
                timeouts = fetch(pending_metrics.get("straggler_timeout"))
                stale_rows = fetch(pending_metrics.get("stale_infill"))
                probe = pending_metrics.get(health.PROBE_KEY)
                nan_rows = (
                    fetch(probe.get("worker_nan_rows")) if probe is not None else None
                )

                def rows(vector):
                    # (n,) -> one step; (K, n) -> one row per scanned step
                    if vector is None:
                        return None
                    return vector[None] if vector.ndim == 1 else vector
                dist, rep, nan_rows = rows(dist), rows(rep), rows(nan_rows)
                timeouts, stale_rows = rows(timeouts), rows(stale_rows)
                regime = None if regime is None else np.atleast_1d(regime)
                nb = max(
                    v.shape[0] for v in (dist, rep, nan_rows, regime, timeouts)
                    if v is not None
                ) if any(
                    v is not None for v in (dist, rep, nan_rows, regime, timeouts)
                ) else 0
                for i in range(nb):
                    ridx = None if regime is None else int(regime[min(i, regime.shape[0] - 1)])
                    ledger.observe(
                        pending_start + i + 1,
                        worker_sq_dist=None if dist is None else dist[i],
                        worker_nan=None if nan_rows is None else nan_rows[i],
                        reputation=None if rep is None else rep[i],
                        regime=ridx,
                        regime_desc=(
                            chaos.describe(ridx)
                            if (ridx is not None and chaos is not None) else None
                        ),
                        # named forgery evidence from the submission
                        # authenticator (reject-and-name, secure/submit.py)
                        forgery=secure_verdicts.pop(pending_start + i + 1, None),
                        # bounded-wait deadline verdicts (straggler_timeout
                        # evidence; explains the timed-out rows' NaN flags)
                        timeout=None if timeouts is None else timeouts[i],
                        # stale infills: named stale_infill evidence, so
                        # late-but-honest stays distinguishable (they still
                        # spent the f budget — docs/engine.md)
                        stale=None if stale_rows is None else stale_rows[i],
                    )

        def probe_clean(dispatch_metrics):
            """Is the state this dispatch produced healthy by the probe?
            Gates the last-known-good pin at checkpoint time."""
            view = health.host_view(dispatch_metrics)
            if view is None:
                return True
            return bool(
                np.all(view["loss_finite"])
                and np.all(np.isfinite(view["update_norm"]))
                and np.all(np.asarray(view["spike"]) <= guardian.spike_factor)
            )

        def do_rollback(at_step):
            """Rollback-and-escalate: restore last-known-good, perturb the
            RNG, climb one ladder rung, discard the abandoned timeline."""
            nonlocal state, step, ts, overrides, chaos_regime_seen
            nonlocal pending_loss, pending_metrics, diverged
            reason = watchdog.last_reason or "divergence"
            if watchdog.exhausted:
                diverged = True
                raise UserException(
                    "guardian: run failed — %s after %d recovery attempt(s) "
                    "(ladder %s)" % (reason, watchdog.attempts,
                                     guardian.ladder.describe())
                )
            checkpoints.wait()  # writer queue flushed before reading targets
            target = checkpoints.pinned_step()
            rstep = target if target is not None else 0
            attempt = watchdog.note_rollback(rstep)
            warning(
                "guardian: %s — rolling back from step %d to %s (attempt %d/%d)"
                % (reason, at_step,
                   "step %d" % rstep if target is not None else "a fresh state",
                   attempt + 1, guardian.retries)
            )
            summaries.event(at_step, "guardian_rollback", {
                "reason": reason, "from_step": int(at_step), "to_step": int(rstep),
                "attempt": attempt, "restored_snapshot": target is not None,
            })
            g_rollbacks.inc()
            # the ring still holds the diverged timeline's per-step rows —
            # dump them before the restore wipes the state
            flight_postmortem("guardian_rollback", at_step)
            if ledger is not None:
                # the replay window re-observes the truncated steps; the
                # rollback event (stamped at the restore step so it survives
                # the truncation) keeps the audit trail of WHY
                ledger.truncate_after(rstep)
                forensics_fed["start"] = None
                ledger.note_guardian(rstep, "rollback", {
                    "reason": reason, "from_step": int(at_step),
                    "attempt": attempt,
                })
            # abandoned verdicts: the replay window re-verifies its steps
            # (the tag chain keeps the abandoned timeline — it is an
            # append-only audit of everything the aggregator verified)
            secure_verdicts.clear()
            secure_fed["start"] = None
            rung = guardian.ladder.rung(attempt)
            if rung is not None:
                try:
                    new_overrides = rung.apply(overrides)
                    with Context("escalate"):
                        new_ts = instrument_stack(build_training(new_overrides))
                    if ts.bounded_step is not None:
                        ts.bounded_step.close()  # retire the old pool
                    overrides, ts = new_overrides, new_ts
                    if custody is not None:
                        # manifests saved from here on sign the new spec
                        custody.gar_spec = overrides.describe()
                    info("guardian: escalated — %s (now %s)"
                         % (rung.describe(), overrides.describe()))
                    summaries.event(rstep, "guardian_escalation", {
                        "rung": rung.describe(), "attempt": attempt,
                        "overrides": overrides.describe(),
                    })
                    g_escalations.inc()
                    note_escalation(rstep, rung, overrides)
                    if ledger is not None:
                        ledger.note_guardian(rstep, "escalation", {
                            "rung": rung.describe(),
                            "overrides": overrides.describe(),
                        })
                except UserException as exc:
                    warning(
                        "guardian: escalation rung %r rejected (%s); retrying "
                        "with the current configuration" % (rung.describe(), exc)
                    )
            # RNG perturbation breaks deterministic re-divergence: the same
            # snapshot + the same streams would replay the exact trajectory
            # that just failed.  Restored runs fold the attempt into the
            # restored key; from-scratch retries move the seed.
            fresh = ts.make_fresh_state(
                args.seed if target is not None
                else args.seed + RESEED_STRIDE * (attempt + 1)
            )
            if target is not None:
                carry, momentum = fresh.carry, fresh.momentum
                template = jax.device_get(fresh.replace(carry=None, momentum=None))
                restored, rstep = checkpoints.restore(template, step=target)
                restored = restored.replace(rng=jax.device_get(
                    jax.random.fold_in(jnp.asarray(restored.rng), RNG_PERTURB_TAG + attempt)
                ))
                state = ts.engine.put_state(
                    restored.replace(carry=carry, momentum=momentum)
                )
            else:
                state = fresh
            step = rstep
            pending_loss = pending_metrics = None
            # the abandoned timeline: snapshots and eval rows beyond the
            # restore point would otherwise poison a later auto-restore /
            # interleave with the retry's rows
            checkpoints.discard_after(rstep)
            eval_file.truncate_after(rstep)
            for trigger in (eval_trigger, ckpt_trigger, summary_trigger):
                if trigger.last_step is not None and trigger.last_step > rstep:
                    trigger.last_step = rstep
            reset_input(rstep, reseed=attempt + 1)
            if chaos is not None:
                chaos_regime_seen = chaos.regime_at(step)

        def observe_pending():
            """Feed the forensics ledger and the watchdog the previous
            dispatch's diagnostics, one observation per completed step.
            Returns True when a rollback happened — the caller discards its
            in-flight results."""
            nonlocal pending_loss, pending_metrics
            feed_pending_secure()
            feed_pending_forensics()
            if watchdog is None or pending_metrics is None:
                return False
            with trace.span("block.probe_fetch", cat="guardian"):
                view = health.host_view(pending_metrics)
                losses = np.atleast_1d(np.asarray(jax.device_get(pending_loss)))
                timeouts = pending_metrics.get("nb_timeouts")
                if timeouts is not None:
                    timeouts = np.atleast_1d(np.asarray(jax.device_get(timeouts)))
            start = pending_start
            pending_loss = pending_metrics = None
            if view is None:  # engine built without the probe
                return False
            finite = np.atleast_1d(view["loss_finite"]).astype(bool)
            spikes = np.atleast_1d(view["spike"]).astype(np.float64)
            for i in range(losses.shape[0]):
                action = watchdog.observe(
                    start + i + 1, float(losses[i]), bool(finite[i]), float(spikes[i])
                )
                if action is None and timeouts is not None:
                    # bounded-wait escalation input: timeouts beyond the
                    # declared budget, sustained, roll back and climb the
                    # ladder (f+K re-sizes the budget for the observed tail)
                    action = watchdog.observe_timeouts(
                        start + i + 1, int(timeouts[i]), overrides.f
                    )
                if (action is None and ts.bounded_step is not None
                        and ts.bounded_step.controller is not None):
                    # adaptive-deadline escalation input: a controller
                    # pinned at its ceiling means the arrival tail outgrew
                    # the budgeted window (parallel/deadline.py)
                    action = watchdog.observe_ceiling(
                        start + i + 1, ts.bounded_step.controller.at_ceiling
                    )
                if action == "recovered":
                    info("guardian: recovered — %d healthy step(s) since the "
                         "last rollback" % guardian.recover_after)
                    summaries.event(start + i + 1, "guardian_recovered", {
                        "attempt": watchdog.attempts - 1,
                        "overrides": overrides.describe(),
                    })
                    g_recoveries.inc()
                    if ledger is not None:
                        ledger.note_guardian(start + i + 1, "recovered", {
                            "attempt": watchdog.attempts - 1,
                        })
                elif action == "rollback":
                    do_rollback(start + i + 1)
                    return True
            return False

        # Host-gap span: the wall time between one dispatch returning and
        # the next one starting (input, cadences, watchdog) — the "off-
        # graph" slice of the perf report, now visible per step in the
        # trace.  Manual start/stop because its lifetime spans loop turns.
        gap = {"span": None}

        def gap_open():
            if trace.installed() is not None:
                gap["span"] = trace.span("host_gap", cat="train").start()

        def gap_close():
            if gap["span"] is not None:
                gap["span"].stop()
                gap["span"] = None

        # Chaos regime transition logging: host-side tracking of the regime
        # governing the NEXT step to dispatch (under --unroll, transitions
        # inside a chunk surface at the chunk boundary).
        chaos_regime_seen = None
        if chaos is not None:
            chaos_regime_seen = chaos.regime_at(step)
            info("Chaos regime at step %d: %s" % (step, chaos.describe(chaos_regime_seen)))
        try:
            while True:
                if step >= max_step or stop["requested"]:
                    # Exit drains the lagged observation first: a guardian
                    # rollback here re-enters training from the restored
                    # step instead of returning with a poisoned tail.
                    if observe_pending() and step < max_step and not stop["requested"]:
                        continue
                    check_divergence()
                    break
                if args.trace and step == offstep + 2:  # skip compile + warmup step
                    import jax.profiler

                    trace_ctx = jax.profiler.trace(args.trace_dir)
                    trace_ctx.__enter__()
                if xprof is not None:
                    # programmatic device capture over an explicit step
                    # window; under --unroll the boundary lands on the
                    # chunk boundary (a compiled scan is never split)
                    xprof.maybe_start(step)
                chunk = 1
                if ts.multi_fn is not None and max_step - step >= unroll and trace_ctx is None:
                    # Unrolled dispatch: K distinct batches, one executable
                    # (device-sampled: the resident dataset IS the input and
                    # the trainer draws its own fresh per-step batches)
                    with trace.span("input", cat="train"):
                        if ts.device_dataset is not None:
                            device_chunk = ts.device_dataset
                        elif chunk_pipeline is not None:
                            device_chunk = next(chunk_pipeline)
                        else:
                            device_chunk = ts.engine.shard_batches(next_chunk())
                    gap_close()
                    perf.step_begin()
                    with xprof.annotate(step) if xprof is not None else contextlib.nullcontext():
                        state, many = ts.multi_fn(state, device_chunk)
                    if observe_pending():
                        continue  # previous chunk diverged: this one is abandoned
                    check_divergence()
                    metrics = jax.tree_util.tree_map(lambda x: x[-1], many)
                    perf.step_end(unroll)
                    gap_open()
                    chunk = unroll
                    pending_loss = many["total_loss"]  # full vector: see check_divergence
                    pending_metrics = many
                    pending_start = step
                elif ts.sampled_tail is not None:
                    # Device-sampled tail: the final (max_step - step) <
                    # unroll steps — and --trace windows, one step per
                    # dispatch so the profiler window sees step boundaries —
                    # run through a tail-sized SAMPLED executable.  Every
                    # step of a device-input run is device-sampled; no
                    # host-batch fallback remains.  The tail length is a
                    # pure function of (max_step, offstep, unroll), so the
                    # executable compiles once per run (asserted by
                    # tests/test_input_pipeline.py's compile-count test).
                    nb_steps = 1 if trace_ctx is not None else max_step - step
                    tail_fn = ts.sampled_tail(nb_steps)
                    gap_close()
                    perf.step_begin()
                    with xprof.annotate(step) if xprof is not None else contextlib.nullcontext():
                        state, many = tail_fn(state, ts.device_dataset)
                    if observe_pending():
                        continue  # previous chunk diverged: this one is abandoned
                    check_divergence()
                    metrics = jax.tree_util.tree_map(lambda x: x[-1], many)
                    perf.step_end(nb_steps)
                    gap_open()
                    chunk = nb_steps
                    pending_loss = many["total_loss"]
                    pending_metrics = many
                    pending_start = step
                else:
                    if chunk_pipeline is not None:
                        # Entering the per-step tail: retire the chunk
                        # producer FIRST — its daemon shares train_iter and
                        # numpy Generators are not thread-safe.
                        chunk_pipeline.close()
                        chunk_pipeline = None
                    with trace.span("input", cat="train"):
                        batch = next(prefetcher) if prefetcher is not None else ts.engine.shard_batch(next(train_iter))
                    gap_close()
                    perf.step_begin()
                    with xprof.annotate(step) if xprof is not None else contextlib.nullcontext():
                        state, metrics = ts.step_fn(state, batch)
                    if observe_pending():
                        continue  # previous step diverged: this one is abandoned
                    check_divergence()
                    perf.step_end()
                    gap_open()
                    pending_loss = metrics["total_loss"]
                    pending_metrics = metrics
                    pending_start = step
                step += chunk
                c_wire_bytes.inc(chunk * wire_step_bytes)
                live_state["step"] = step
                if xprof is not None:
                    xprof.maybe_stop(step)
                if chaos is not None:
                    regime_now = chaos.regime_at(step)
                    if regime_now != chaos_regime_seen:
                        chaos_regime_seen = regime_now
                        info("Chaos regime switch at step %d: now %s"
                             % (step, chaos.describe(regime_now)))
                        summaries.event(step, "chaos_regime_switch", {
                            "regime": regime_now,
                            "spec": chaos.describe(regime_now),
                        })
                if trace_ctx is not None and step >= offstep + 5:
                    trace_ctx.__exit__(None, None, None)
                    trace_ctx = None
                    info("Profiler trace written to %r" % args.trace_dir)
                if eval_trigger.should_fire(step):
                    check_divergence()
                    run_eval(step)
                    eval_trigger.fired(step)
                if save_snapshots and ckpt_trigger.should_fire(step):
                    check_divergence()
                    checkpoints.wait()  # surface a previous write's failure
                    checkpoints.save(state, step)
                    if watchdog is not None and watchdog.healthy and probe_clean(
                        pending_metrics if pending_metrics is not None else metrics
                    ):
                        # last-known-good: this snapshot survives pruning and
                        # is the rollback target (obs/checkpoint.py pin).
                        # pending_metrics is the WHOLE last dispatch — under
                        # --unroll every sub-step must read clean, not just
                        # the chunk's final slice
                        checkpoints.pin(step)
                    ckpt_trigger.fired(step)
                if summary_trigger.should_fire(step):
                    check_divergence()
                    with trace.span("summaries", cat="obs"):
                        summaries.scalars(step, summary_scalars(step, metrics))
                    dump_metrics_file()
                    summary_trigger.fired(step)
        finally:
            for signum, handler in previous_handlers.items():
                signal.signal(signum, handler)
            if trace_ctx is not None:
                trace_ctx.__exit__(None, None, None)
            if xprof is not None:
                xprof.close()
            aborting = sys.exc_info()[0] is not None
            # Final fire of every daemon (reference: runner.py:356-494 at
            # stop) — skipped on divergence (evaluating or checkpointing the
            # NaN state would poison the next run's auto-restore) and when
            # the trigger already fired at this exact step.
            if step > offstep and not diverged:
                if eval_trigger.enabled and eval_trigger.last_step != step:
                    run_eval(step)
                if save_snapshots and ckpt_trigger.last_step != step:
                    checkpoints.save(state, step)
                if metrics and summary_trigger.last_step != step:
                    summaries.scalars(step, summary_scalars(step, metrics))
            if (step > offstep and not diverged and not aborting
                    and not stop["requested"]):
                # Regression sentinel at run end (obs/slo.py): judge the
                # run's measured throughput metrics against the stored
                # baseline, and/or capture a fresh baseline.  Before
                # summaries.close() — the verdict is a summary event too.
                # Signal-interrupted runs are NOT judged: a truncated run's
                # throughput is meaningless against a full-run baseline, and
                # a supervisor's graceful retune restart must not synthesize
                # a REGRESS verdict (docs/operations.md).
                if sentinel is not None or args.slo_capture:
                    slo_current = obs_slo.collect_current(registry, perf)
                if sentinel is not None:
                    verdict = sentinel.verdict(slo_current, run_id=run_id)
                    live_state["slo"] = verdict
                    info(obs_slo.describe_verdict(verdict))
                    summaries.event(step, "slo_verdict", {
                        "verdict": verdict["verdict"],
                        "regressed": verdict["regressed"],
                        "checks": verdict["checks"],
                    })
                    if args.slo_verdict and lead:
                        obs_slo.save_verdict(args.slo_verdict, verdict)
                        info("SLO verdict -> %r" % args.slo_verdict)
                if args.slo_capture and lead:
                    doc = obs_slo.capture(args.slo_capture, slo_current,
                                          run_id=run_id)
                    info("SLO baseline -> %r (metrics: %s)" % (
                        args.slo_capture, ", ".join(sorted(doc["metrics"]))))
            if prefetcher is not None:
                prefetcher.close()
            if chunk_pipeline is not None:
                chunk_pipeline.close()
            if ts.bounded_step is not None:
                ts.bounded_step.close()
            eval_file.close()
            summaries.close()
            gap_close()
            # Telemetry flush — last observations (a diverged tail IS
            # evidence), attribution report, metrics dump, trace.  Every
            # step is INDEPENDENT: a failing ledger save must not skip the
            # metrics dump (a preempted run must never exit with an empty
            # --metrics-file), and during an abort no flush failure may
            # mask the propagating training error.
            flush_errors = []

            def flush(label, fn):
                try:
                    fn()
                except Exception as exc:
                    # always LOGGED here (a later cleanup failure must not
                    # erase the record); re-raised at the very end unless
                    # an exception is already propagating
                    warning("Telemetry flush (%s) failed: %s" % (label, exc))
                    if not aborting:
                        flush_errors.append((label, exc))

            if aborting or diverged:
                # the ring holds the exact per-step window that killed the
                # run — dump it before anything else can fail
                flush("flight-postmortem", lambda: flight_postmortem(
                    "divergence" if diverged else "crash", step))
            # Drain the lagged feeds BEFORE the report is written: the
            # final dispatch's evidence — and its secure verdict lane —
            # must reach the ledger (they sit one dispatch behind by
            # design, so shutdown is the only place they can land).
            flush("secure-drain", feed_pending_secure)
            flush("forensics-drain", feed_pending_forensics)
            if args.journal and obs_events.installed() is not None:
                # run_end closes the causal timeline BEFORE the forensics
                # report is written, so the report's journal section counts
                # every event of the run (incl. this one)
                def journal_run_end():
                    journal = obs_events.installed()
                    obs_events.emit(
                        "run_end", step=step, diverged=diverged,
                        aborting=aborting,
                        forensics=args.forensics if ledger is not None else None,
                    )
                    if ledger is not None:
                        ledger.note_journal(
                            journal.path, journal.counts_by_type()
                        )

                flush("journal-end", journal_run_end)
            if ledger is not None:
                def save_forensics():
                    md_path = (
                        args.forensics[:-5] + ".md"
                        if args.forensics.endswith(".json") else args.forensics + ".md"
                    )
                    report = ledger.save(args.forensics, markdown_path=md_path)
                    suspects = report["suspects"]
                    info("Forensics report -> %r (%s)" % (
                        args.forensics,
                        "Byzantine worker(s): %s" % ", ".join(map(str, suspects))
                        if suspects else "no worker attributed Byzantine",
                    ))

                flush("forensics-report", save_forensics)
            flush("metrics-file", dump_metrics_file)
            if args.trace_file:
                def save_span_trace():
                    written = trace.uninstall(save=True)
                    if written:
                        info("Span trace -> %r (run_id %s)" % (written, run_id))

                flush("trace", save_span_trace)
            if args.journal and obs_events.installed() is not None:
                def close_journal():
                    written = obs_events.uninstall()
                    if written:
                        info("Run journal -> %r (run_id %s)" % (written, run_id))

                flush("journal-close", close_journal)
            if live is not None:
                flush("live-exporter", live.shutdown_all)
            perf.report()
            if checkpoints is not None:
                # LAST cleanup step, so a flush failure can no longer skip
                # the closes/report above: a returned run is fully flushed
                # to disk.  If an exception is already propagating, the
                # flush failure must not mask it — log it instead.
                if aborting:
                    try:
                        checkpoints.wait(shutdown=True)
                    except Exception as exc:
                        warning("Checkpoint write failed during abort: %s" % exc)
                else:
                    checkpoints.wait(shutdown=True)
            if flush_errors:
                # surfaced LAST so a telemetry write failure can no longer
                # skip the report or the checkpoint flush (it still fails
                # the run: silent telemetry loss is how evidence vanishes)
                label, exc = flush_errors[0]
                if len(flush_errors) > 1:
                    warning("%d more telemetry flush step(s) failed after %r"
                            % (len(flush_errors) - 1, label))
                raise exc
    return 0


def cli():
    from . import console_entry

    return console_entry(main)


if __name__ == "__main__":
    sys.exit(cli())

"""Fleet router runner: ONE admission port in front of N serving processes.

The traffic plane's CLI (``serve/router.py``, docs/serving.md "The traffic
plane"): point it at N independent ``cli/serve.py`` processes following
the same snapshot stream and it serves ``POST /predict`` on a single
port, routing on the pure least-in-flight policy with the fleet-consistent
``weights_step`` guarantee, fleet-decision shed (429 only when EVERY
healthy backend is saturated), drain re-routing (a SIGTERM'd backend takes
no new traffic) and exactly-once re-dispatch when a backend dies
mid-flight.

Health and pressure come from the PR-15 fleet scrape: the router embeds a
:class:`~aggregathor_tpu.obs.fleet.FleetCollector` polling every backend's
``/metrics`` + ``/status`` (``--poll-interval`` / ``--down-after``), and
per-request outcomes latch a dead backend out ahead of the scrape.  The
router exports its own ``/metrics`` and ``/status``, so an outer
``python -m aggregathor_tpu.obs.fleet`` scrapes the router like any other
instance; with ``--journal`` every routing decision (``router_route`` /
``router_shed`` / ``router_retry`` / ``router_backend_down`` /
``router_backend_up`` / ``router_drain`` / ``router_step_pin``) lands in
the causal run journal.

Example (two backends, one door)::

  python -m aggregathor_tpu.cli.router \
      --backend a=127.0.0.1:8000 --backend b=127.0.0.1:8001 \
      --port 8100 --journal out/router_journal.jsonl
"""

import argparse
import os
import signal
import sys
import threading


def build_parser():
    parser = argparse.ArgumentParser(
        prog="aggregathor-tpu router",
        description="fleet admission + routing in front of replicated serving",
    )
    parser.add_argument("--backend", action="append", default=[], required=True,
                        metavar="NAME=HOST:PORT",
                        help="one serving backend (repeatable); NAME keys the "
                             "journal/metrics, HOST:PORT its /predict surface")
    parser.add_argument("--host", default="127.0.0.1", help="bind address")
    parser.add_argument("--port", type=int, default=8100,
                        help="admission port (0 = ephemeral)")
    parser.add_argument("--poll-interval", type=float, default=0.5, metavar="S",
                        help="fleet scrape period (health/pressure sampling)")
    parser.add_argument("--down-after", type=int, default=3, metavar="N",
                        help="consecutive scrape misses before a backend reads "
                             "down (a failed forward latches it out immediately)")
    parser.add_argument("--scrape-timeout", type=float, default=2.0, metavar="S",
                        help="per-backend scrape fetch timeout")
    parser.add_argument("--request-timeout", type=float, default=60.0, metavar="S",
                        help="forward timeout for /predict (must exceed the "
                             "backends' own batch wait)")
    parser.add_argument("--step-wait", type=float, default=5.0, metavar="S",
                        help="how long a step-pinned request may wait out a "
                             "swap window before 503 (consistency over "
                             "availability, bounded)")
    parser.add_argument("--ready-file", default=None, metavar="PATH",
                        help="write 'host port pid' here once the first fleet "
                             "scrape ran AND the port is bound (harness handshake)")
    parser.add_argument("--journal", default=None, metavar="JSONL",
                        help="causal run journal (obs/events.py): append every "
                             "routing decision as typed JSONL (schema "
                             "aggregathor.obs.events.v2)")
    parser.add_argument("--run-id", default=None, metavar="ID",
                        help="run id stamped on journal lines (default: generated)")
    from . import add_causal_flags

    add_causal_flags(parser)
    return parser


def parse_backends(specs):
    from ..utils import UserException

    backends = {}
    for spec in specs:
        name, sep, url = spec.partition("=")
        if not sep or not name or not url:
            raise UserException(
                "--backend %r: expected NAME=HOST:PORT" % spec)
        if name in backends:
            raise UserException("--backend: name %r given twice" % name)
        backends[name] = url
    return backends


def main(argv=None):
    args = build_parser().parse_args(argv)

    from ..obs import events as obs_events
    from ..obs.summaries import make_run_id
    from ..serve import FleetRouter, RouterServer
    from ..utils import info

    from . import parse_cause_flag

    backends = parse_backends(args.backend)
    run_id = args.run_id if args.run_id else make_run_id()
    cause = parse_cause_flag(args.cause)
    if args.journal:
        obs_events.install(args.journal, run_id=run_id,
                           max_bytes=args.journal_max_bytes)
        obs_events.emit("run_start", role="router",
                        backends=sorted(backends), pid=os.getpid(),
                        cause=cause)
        info("Run journal to %r (run_id %s)" % (args.journal, run_id))

    router = FleetRouter(
        backends,
        poll_interval=args.poll_interval,
        down_after=args.down_after,
        timeout=args.scrape_timeout,
        request_timeout_s=args.request_timeout,
        step_wait_s=args.step_wait,
    )
    server = RouterServer(router, host=args.host, port=args.port)

    stop = threading.Event()

    def on_signal(signum, frame):
        info("Signal %d: router shutting down" % signum)
        stop.set()

    previous = {
        signal.SIGINT: signal.signal(signal.SIGINT, on_signal),
        signal.SIGTERM: signal.signal(signal.SIGTERM, on_signal),
    }
    try:
        router.start()  # one scrape up front: the first request sees the fleet
        host, port = server.serve_background()
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as fd:
                fd.write("%s %d %d\n" % (host, port, os.getpid()))
            os.replace(tmp, args.ready_file)  # atomic: never a torn line
        info("Routing %d backend(s): %s"
             % (len(backends), ", ".join(sorted(backends))))
        stop.wait()
    finally:
        for signum, handler in previous.items():
            signal.signal(signum, handler)
        server.shutdown_all()
        router.close()
        if args.journal and obs_events.installed() is not None:
            obs_events.emit("run_end", role="router")
            written = obs_events.uninstall()
            info("Run journal -> %r (run_id %s)" % (written, run_id))
    return 0


def cli():
    from . import console_entry

    return console_entry(main)


if __name__ == "__main__":
    sys.exit(cli())

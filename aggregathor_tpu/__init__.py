"""AggregaThor-TPU: Byzantine-resilient distributed SGD, TPU-native.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the SysML'19
AggregaThor framework (reference: LPD-EPFL/AggregaThor).  Instead of a
TensorFlow-1 parameter-server cluster with a patched gRPC/MPI/UDP transport,
training is a single-controller SPMD program over a `jax.sharding.Mesh`:

- each of the ``n`` logical Byzantine-ML *workers* is a mesh slot (TPU core or
  a shard group); per-worker gradients are computed in isolation under
  ``shard_map`` (reference: graph.py:248-273);
- the parameter server disappears: the robust Gradient Aggregation Rule (GAR)
  runs jit-compiled on-device on an `(n, d)` view of the per-worker gradients
  that is *dimension-sharded* — an ``all_to_all`` reshards from worker-sharded
  to column-block-sharded, pairwise distances are reduced with a tiny ``psum``,
  and coordinate-wise selection runs locally per block, so per-device memory
  stays O(d) instead of O(n*d) (replaces tf_patches/ transports, see
  SURVEY.md §2.6);
- Byzantine behaviour is modeled explicitly by attack transforms applied to a
  worker's own gradient slot before aggregation (implements the reference's
  acknowledged TODO at runner.py:345), and the UDP lossy-transport semantics
  (lost packets -> NaN coordinates, mpi_rendezvous_mgr.patch:833-841) map to a
  deterministic NaN-masking "lossy link" simulator.

Subpackages
-----------
- ``core``     flatten/unflatten machinery, schedules, optimizers, train state
- ``gars``     the GAR registry and rules (numpy oracle / jnp / pallas tiers)
- ``ops``      low-level kernels: Pallas TPU kernels + C++ host-native library
- ``parallel`` mesh construction, worker isolation, distributed GAR engine,
               attacks, lossy-link simulation
- ``models``   experiment (model+dataset) plugins: mnist, cnnet, resnets, ...
- ``obs``      logging-adjacent observability: eval TSV, checkpoints, metrics
- ``cli``      the runner / deploy command-line entry points
- ``utils``    context logging, class registry, key:value argument parsing
"""

__version__ = "0.1.0"

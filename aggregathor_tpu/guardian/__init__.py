"""guardian/ — in-loop divergence watchdog and rollback-and-escalate recovery.

The aggregation rules in ``gars/`` defend each STEP; the guardian defends
the RUN.  Three layers (docs/guardian.md):

1. **Health probe** (``probe.py``) — finite-loss flag, aggregated-update
   norm, EMA loss-spike score and per-worker NaN-row flags, computed inside
   the jitted step of both engines and returned with the step metrics at
   zero extra compiles;
2. **Watchdog + escalation** (``watchdog.py``, ``escalate.py``) — a
   host-side policy that, on sustained divergence, has the runner restore
   the last-known-good snapshot (``obs/checkpoint.py`` pin policy), perturb
   the restored RNG, and climb a configurable escalation ladder (raise
   ``f`` -> stronger GAR -> quarantine -> damp the lr) with bounded retries
   and exponential backoff;
3. **Preemption-safe resume** (``cli/runner.py``) — SIGTERM/SIGINT flushes
   background checkpoint writes and exits restorably; restore is
   bit-identical on step/params/opt-state/RNG (the input iterator
   fast-forwards to the restored step).
"""

from .escalate import (  # noqa: F401
    DEFAULT_LADDER,
    RESEED_STRIDE,
    RNG_PERTURB_TAG,
    EscalationLadder,
    Overrides,
    note_escalation,
)
from .probe import (  # noqa: F401
    EMA_DECAY,
    EMA_UNSET,
    PROBE_KEY,
    host_view,
    probe_metrics,
    spike_score,
    update_loss_ema,
)
from .watchdog import GuardianConfig, Watchdog  # noqa: F401

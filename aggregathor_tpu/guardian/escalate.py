"""Escalation ladder: what rollback *changes* so the retry can succeed.

Restoring the last-good snapshot alone only helps against transient faults;
a regime that exceeds the configured rule's breakdown point (schedulable via
``chaos/``) would deterministically re-diverge.  Each rollback therefore
climbs one rung of a configurable ladder of defensive overrides — the
meta-aggregation idea (Fault Tolerant ML, arXiv:2405.14759) applied as a
recovery policy instead of a per-step rule.

Grammar (``--guardian-args ladder:RUNG,RUNG,...``)::

  LADDER := RUNG ("," RUNG)*
  RUNG   := "f+K"                      raise the declared Byzantine count by K
          | "gar=NAME[/key:val...]"    swap to GAR NAME (sub-args '/'-separated)
          | "quarantine[=DECAY/THR]"   engage reputation quarantine
          | "lr*X"                     scale the learning rate by X in (0, 1]

Rungs apply CUMULATIVELY: after two rollbacks with the default ladder the
run trains with f+1 AND the median rule.  Overrides are expressed as a
:class:`Overrides` record the runner's training-stack builder consumes;
rungs never mutate live engines — the runner rebuilds (one recompile per
escalation, paid only on the rare recovery path).
"""

from ..utils import UserException

#: the default ladder: cheapest assumption-widening first, then stronger
#: rules (average -> median -> bulyan is the canonical GAR strength order,
#: docs/robustness.md), then active exclusion, then step-size damping
DEFAULT_LADDER = "f+1,gar=median,gar=bulyan,quarantine,lr*0.5"

#: fold_in tag perturbing a restored RNG per rollback attempt — shared by
#: the runner and the campaign harness so the two recovery paths never
#: silently desynchronize their retry streams
RNG_PERTURB_TAG = 0x6A12D1A

#: seed stride for from-scratch retries / input-stream reseeds (prime, so
#: strided seeds never collide with the +1/+2 offsets runs already use)
RESEED_STRIDE = 7919


class Overrides:
    """The training-stack knobs escalation may change, with their originals.

    The runner builds its engine/step functions from one of these; rungs
    produce a modified copy (`apply` never mutates in place, so a failed
    rebuild can fall back to the previous overrides)."""

    __slots__ = ("f", "gar_name", "gar_args", "lr_scale",
                 "reputation_decay", "quarantine_threshold")

    def __init__(self, f, gar_name, gar_args=(), lr_scale=1.0,
                 reputation_decay=None, quarantine_threshold=0.0):
        self.f = int(f)
        self.gar_name = str(gar_name)
        self.gar_args = tuple(gar_args)
        self.lr_scale = float(lr_scale)
        self.reputation_decay = reputation_decay
        self.quarantine_threshold = float(quarantine_threshold)

    def copy(self):
        return Overrides(self.f, self.gar_name, self.gar_args, self.lr_scale,
                         self.reputation_decay, self.quarantine_threshold)

    def describe(self):
        parts = ["f=%d" % self.f, "gar=%s" % self.gar_name]
        if self.gar_args:
            parts.append("gar-args=%s" % "/".join(self.gar_args))
        if self.lr_scale != 1.0:
            parts.append("lr*%g" % self.lr_scale)
        if self.quarantine_threshold:
            parts.append("quarantine=%g/%g"
                         % (self.reputation_decay, self.quarantine_threshold))
        return " ".join(parts)


class _Rung:
    spec = None

    def describe(self):
        return self.spec

    def apply(self, overrides):
        raise NotImplementedError


class RaiseF(_Rung):
    def __init__(self, spec, k):
        self.spec = spec
        self.k = int(k)

    def apply(self, overrides):
        out = overrides.copy()
        out.f = overrides.f + self.k
        return out


class SwapGar(_Rung):
    def __init__(self, spec, name, args):
        self.spec = spec
        self.name = name
        self.args = tuple(args)

    def apply(self, overrides):
        out = overrides.copy()
        out.gar_name = self.name
        out.gar_args = self.args
        return out


class Quarantine(_Rung):
    def __init__(self, spec, decay=0.9, threshold=0.5):
        self.spec = spec
        self.decay = float(decay)
        self.threshold = float(threshold)

    def apply(self, overrides):
        out = overrides.copy()
        if out.reputation_decay is None:
            out.reputation_decay = self.decay
        out.quarantine_threshold = self.threshold
        return out


class ScaleLr(_Rung):
    def __init__(self, spec, factor):
        self.spec = spec
        self.factor = float(factor)

    def apply(self, overrides):
        out = overrides.copy()
        out.lr_scale = overrides.lr_scale * self.factor
        return out


def _parse_rung(spec):
    if spec.startswith("f+"):
        try:
            k = int(spec[2:])
        except ValueError:
            raise UserException("Ladder rung %r: K in 'f+K' is not an integer" % (spec,))
        if k < 1:
            raise UserException("Ladder rung %r: K must be >= 1" % (spec,))
        return RaiseF(spec, k)
    if spec.startswith("gar="):
        from .. import gars as gar_registry

        body = spec[len("gar="):]
        parts = body.split("/")
        name, args = parts[0], parts[1:]
        if name not in gar_registry.itemize():
            raise UserException(
                "Ladder rung %r: unknown GAR %r (registered: %s)"
                % (spec, name, ", ".join(sorted(gar_registry.itemize())))
            )
        for arg in args:
            if ":" not in arg:
                raise UserException(
                    "Ladder rung %r: GAR sub-arg %r is not key:value" % (spec, arg)
                )
        return SwapGar(spec, name, args)
    if spec == "quarantine" or spec.startswith("quarantine="):
        if spec == "quarantine":
            return Quarantine(spec)
        body = spec[len("quarantine="):]
        try:
            decay_text, threshold_text = body.split("/", 1)
            decay, threshold = float(decay_text), float(threshold_text)
        except ValueError:
            raise UserException(
                "Ladder rung %r: expected quarantine=DECAY/THRESHOLD" % (spec,)
            )
        if not 0.0 < decay < 1.0 or not 0.0 < threshold < 1.0:
            raise UserException(
                "Ladder rung %r: decay and threshold must lie in (0, 1)" % (spec,)
            )
        return Quarantine(spec, decay, threshold)
    if spec.startswith("lr*"):
        try:
            factor = float(spec[3:])
        except ValueError:
            raise UserException("Ladder rung %r: X in 'lr*X' is not a number" % (spec,))
        if not 0.0 < factor <= 1.0:
            raise UserException("Ladder rung %r: X must lie in (0, 1]" % (spec,))
        return ScaleLr(spec, factor)
    raise UserException(
        "Unknown ladder rung %r (expected f+K, gar=NAME[/key:val...], "
        "quarantine[=DECAY/THR], or lr*X)" % (spec,)
    )


def note_escalation(step, rung, overrides):
    """Journal one APPLIED escalation rung (obs/events.py): called by the
    runner's rollback path after the rebuilt training stack is live, so the
    event records what the run actually trains with from ``step`` on — a
    rejected rung (infeasible under the new f, unmaskable GAR) never
    journals.  Pure side-channel: no engine state is touched here."""
    from ..obs import events

    events.emit("guardian_escalation", step=step, rung=rung.describe(),
                overrides=overrides.describe())


class EscalationLadder:
    """Parsed ladder: ``rung(i)`` is the override to stack on attempt i+1
    (None past the end — later retries keep the last escalated config and
    rely on the rollback's RNG perturbation alone)."""

    def __init__(self, spec=DEFAULT_LADDER):
        self.spec = str(spec)
        specs = [s for s in self.spec.split(",") if s]
        if not specs:
            raise UserException("Empty escalation ladder (expected e.g. %r)" % DEFAULT_LADDER)
        self.rungs = [_parse_rung(s) for s in specs]

    def rung(self, index):
        return self.rungs[index] if 0 <= index < len(self.rungs) else None

    def __len__(self):
        return len(self.rungs)

    def describe(self):
        return ",".join(r.describe() for r in self.rungs)

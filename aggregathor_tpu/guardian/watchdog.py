"""Host-side divergence watchdog: probe stream in, rollback decisions out.

The watchdog is deliberately PURE POLICY — it never touches engines, state
or disk.  The runner feeds it one observation per completed training step
(from the in-step health probe, ``guardian/probe.py``, with the same
one-step lag the NaN-abort check already uses) and acts on the returned
decision:

- ``"rollback"``   sustained divergence: restore the last-known-good
  snapshot, perturb the RNG, climb one escalation rung (``escalate.py``);
- ``"recovered"``  the run stayed healthy for ``recover`` steps after a
  rollback: the regression is over, log it and re-arm;
- ``None``         keep training.

Divergence has two modes with different urgencies: a NON-FINITE loss means
the parameters are already poisoned (every later step is garbage), so it
triggers immediately and ignores the cooldown; a finite loss SPIKE
(``spike`` x the EMA reference, probe.py) must persist for ``patience``
consecutive steps, and after a rollback the spike trigger backs off
exponentially (``patience * backoff^attempt`` steps) so each escalated
configuration gets a growing grace window to prove itself while replaying
the regime that broke its predecessor.  ``retries`` bounds the total
rollback count; past it the runner declares the run failed.
"""

import math

from ..obs import events, trace
from ..utils import parse_keyval
from .escalate import DEFAULT_LADDER, EscalationLadder


class GuardianConfig:
    """Parsed ``--guardian-args`` (key:value strings, like every registry).

    Keys: ``patience`` (consecutive spiked steps before rollback, default 3),
    ``spike`` (loss/EMA ratio counted as a spike, default 25), ``retries``
    (max rollbacks before the run is declared failed, default 5), ``backoff``
    (cooldown growth base, default 2), ``recover`` (healthy steps after a
    rollback before declaring recovery, default 10), ``ceiling-patience``
    (consecutive controller-at-ceiling steps before rollback, default
    4 x patience — see ``observe_ceiling``), ``ladder`` (escalation
    rungs, comma-separated — see ``escalate.py`` for the grammar)."""

    DEFAULTS = {
        "patience": 3,
        "spike": 25.0,
        "retries": 5,
        "backoff": 2.0,
        "recover": 10,
        "ceiling-patience": 0,  # 0 = derive as 4 x patience
        "ladder": DEFAULT_LADDER,
    }

    def __init__(self, args=None):
        from ..utils import UserException

        kv = parse_keyval(args or [], dict(self.DEFAULTS), strict=True)
        self.patience = int(kv["patience"])
        self.spike_factor = float(kv["spike"])
        self.retries = int(kv["retries"])
        self.backoff = float(kv["backoff"])
        self.recover_after = int(kv["recover"])
        # sustained controller-at-ceiling is chronic, not acute: give it a
        # longer leash than the loss-spike patience by default
        self.ceiling_patience = int(kv["ceiling-patience"]) or 4 * self.patience
        if self.ceiling_patience < 1:
            raise UserException(
                "guardian ceiling-patience must be >= 1 (got %d)"
                % self.ceiling_patience
            )
        if self.patience < 1:
            raise UserException("guardian patience must be >= 1 (got %d)" % self.patience)
        if self.spike_factor <= 1.0:
            raise UserException(
                "guardian spike must exceed 1 (a ratio of 1 is a flat loss), got %g"
                % self.spike_factor
            )
        if self.retries < 1:
            raise UserException("guardian retries must be >= 1 (got %d)" % self.retries)
        if self.backoff < 1.0:
            raise UserException("guardian backoff must be >= 1 (got %g)" % self.backoff)
        if self.recover_after < 1:
            raise UserException("guardian recover must be >= 1 (got %d)" % self.recover_after)
        self.ladder = EscalationLadder(kv["ladder"])


class Watchdog:
    """Consumes per-step probe readings, emits rollback/recovered decisions."""

    def __init__(self, config):
        self.config = config
        self.attempts = 0          # rollbacks performed so far
        self.unhealthy_streak = 0  # consecutive spiked/non-finite steps
        self.healthy_streak = 0    # consecutive clean steps
        self.recovering = False    # between a rollback and its recovery call
        self.cooldown_until = -1   # spike triggers suppressed below this step
        self.last_reason = None    # human-readable cause of the last rollback
        self.timeout_streak = 0    # consecutive steps with timeouts beyond f
        self.ceiling_streak = 0    # consecutive steps controller-at-ceiling
        #: the journal record of the last guardian_rollback_decision —
        #: note_rollback cites it as the guardian_rollback's cause (the
        #: causal plane: the actuation points at the decision that forced
        #: it, same-journal, so ``instance`` stays None in the reference)
        self._last_decision = None

    @property
    def healthy(self):
        """True when the last observed step was clean — the runner pins a
        snapshot as last-known-good only when this holds at save time."""
        return self.unhealthy_streak == 0

    @property
    def exhausted(self):
        return self.attempts >= self.config.retries

    def observe(self, step, loss, finite, spike):
        """One completed step's probe scalars.  Returns ``"rollback"``,
        ``"recovered"``, or ``None``."""
        finite = bool(finite)
        unhealthy = (not finite) or (spike > self.config.spike_factor)
        if not unhealthy:
            self.healthy_streak += 1
            self.unhealthy_streak = 0
            if self.recovering and self.healthy_streak >= self.config.recover_after:
                self.recovering = False
                trace.instant("guardian.recovered", cat="guardian", step=int(step),
                              attempts=self.attempts)
                events.emit("guardian_recovered", step=step,
                            attempts=self.attempts,
                            healthy_streak=self.healthy_streak)
                return "recovered"
            return None
        self.unhealthy_streak += 1
        self.healthy_streak = 0
        if not finite:
            # params are poisoned: no cooldown, no patience
            self.last_reason = "non-finite loss at step %d" % step
            trace.instant("guardian.rollback_decision", cat="guardian",
                          step=int(step), reason="non-finite")
            self._last_decision = events.emit(
                "guardian_rollback_decision", step=step, reason="non-finite")
            return "rollback"
        if step >= self.cooldown_until and self.unhealthy_streak >= self.config.patience:
            self.last_reason = (
                "loss spike x%.1f sustained %d steps (threshold x%.1f, patience %d)"
                % (spike, self.unhealthy_streak, self.config.spike_factor,
                   self.config.patience)
            )
            trace.instant("guardian.rollback_decision", cat="guardian",
                          step=int(step), reason="spike", spike=float(spike))
            self._last_decision = events.emit(
                "guardian_rollback_decision", step=step,
                reason="spike", spike=float(spike),
                streak=self.unhealthy_streak)
            return "rollback"
        return None

    def observe_timeouts(self, step, nb_timeouts, budget):
        """Bounded-wait escalation input (parallel/bounded.py): timeouts
        BEYOND the declared-f budget spend guarantee the rule does not
        have — sustained for ``patience`` steps (and outside the rollback
        cooldown, like the spike trigger) that is a rollback decision, and
        the ladder's ``f+K`` rung re-sizes the budget for the observed
        tail.  Timeouts within budget are the protocol working as designed
        and reset the streak."""
        if nb_timeouts <= budget:
            self.timeout_streak = 0
            return None
        self.timeout_streak += 1
        if step >= self.cooldown_until and self.timeout_streak >= self.config.patience:
            self.last_reason = (
                "straggler timeouts (%d) beyond the declared budget f=%d "
                "sustained %d steps" % (nb_timeouts, budget, self.timeout_streak)
            )
            trace.instant("guardian.rollback_decision", cat="guardian",
                          step=int(step), reason="straggler_timeouts",
                          nb_timeouts=int(nb_timeouts), budget=int(budget))
            self._last_decision = events.emit(
                "guardian_rollback_decision", step=step,
                reason="straggler_timeouts",
                nb_timeouts=int(nb_timeouts), budget=int(budget),
                streak=self.timeout_streak)
            return "rollback"
        return None

    def observe_ceiling(self, step, at_ceiling):
        """Adaptive-deadline escalation input (parallel/deadline.py): a
        controller pinned at its CEILING means the observed arrival tail
        wants a wider window than the operator budgeted — the fleet's tail
        has outgrown the declared deadline, a capacity regression the same
        way over-budget timeouts are.  Sustained for ``ceiling-patience``
        steps (and outside the rollback cooldown) that is a rollback
        decision; the ladder's ``f+K`` rung re-sizes the budget so more of
        the tail may be dropped instead of waited on.  Any un-pinned step
        resets the streak."""
        if not at_ceiling:
            self.ceiling_streak = 0
            return None
        self.ceiling_streak += 1
        if (step >= self.cooldown_until
                and self.ceiling_streak >= self.config.ceiling_patience):
            self.last_reason = (
                "deadline controller pinned at its ceiling for %d steps "
                "(the arrival tail outgrew the budgeted window)"
                % self.ceiling_streak
            )
            trace.instant("guardian.rollback_decision", cat="guardian",
                          step=int(step), reason="deadline_ceiling",
                          streak=int(self.ceiling_streak))
            self._last_decision = events.emit(
                "guardian_rollback_decision", step=step,
                reason="deadline_ceiling",
                streak=int(self.ceiling_streak))
            return "rollback"
        return None

    def note_rollback(self, restore_step):
        """Record that the runner executed a rollback landing at
        ``restore_step``; returns the 0-based attempt index (= the
        escalation rung to climb).  The spike cooldown grows exponentially
        with the attempt count — each escalated configuration gets a longer
        window to replay the hostile regime before being judged."""
        attempt = self.attempts
        self.attempts += 1
        self.unhealthy_streak = 0
        self.healthy_streak = 0
        self.timeout_streak = 0
        self.ceiling_streak = 0
        self.recovering = True
        grace = math.ceil(self.config.patience * self.config.backoff ** self.attempts)
        self.cooldown_until = restore_step + grace
        trace.instant("guardian.rollback", cat="guardian",
                      restore_step=int(restore_step), attempt=attempt,
                      cooldown_until=int(self.cooldown_until))
        decision, self._last_decision = self._last_decision, None
        events.emit("guardian_rollback", step=restore_step,
                    reason=self.last_reason, attempt=attempt,
                    cooldown_until=int(self.cooldown_until),
                    cause=(events.cause_of(decision)
                           if decision is not None else None))
        return attempt

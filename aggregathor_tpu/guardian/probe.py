"""In-step health probe: the traced fields both engines attach to metrics.

AggregaThor's GARs give *per-step* resilience only while the real Byzantine
count stays within the declared ``f``; beyond the breakdown point training
silently diverges (PAPER.md; the empirical boundary is measured by
``chaos/campaign.py --breakdown``).  The guardian's first layer is a health
probe computed INSIDE the jitted step — it rides the existing metrics
dictionary, so collecting it costs zero extra dispatches and zero extra
compiles (asserted by tests/test_guardian.py):

- ``loss_finite``      int32 0/1 — is this step's total loss finite;
- ``update_norm``      f32 — L2 norm of the aggregated update the optimizer
  consumed (the same value as ``grad_norm``, re-exported under the probe
  contract so watchdog consumers need only one key family);
- ``spike``            f32 — ratio of this step's |loss| to the EMA of the
  recent |loss| (``EMA_DECAY``); 1.0 while the EMA is still unset, ``inf``
  when the loss is non-finite.  A sustained large ratio is the probe's
  "diverging but not yet NaN" signal;
- ``worker_nan_rows``  (n,) int32 0/1 — which workers' POST-TRANSPORT
  submissions contained any non-finite coordinate this step (lossy NaN
  infill, dropped stragglers, ``inf`` attacks) — distinguishes "the model
  is sick" from "the network is eating rows".

The EMA lives in ``TrainState.loss_ema`` (a replicated scalar side buffer,
never serialized — it re-warms from :data:`EMA_UNSET` after any restore, so
a rollback never compares post-recovery losses against a poisoned EMA).
"""

import jax.numpy as jnp

#: metrics key under which both engines nest the probe fields
PROBE_KEY = "probe"

#: EMA decay of the |loss| reference the spike score divides by — smoothed
#: enough to ride out batch noise, fresh enough that a real regression
#: dominates it within ~10 steps
EMA_DECAY = 0.9

#: sentinel for "no EMA accumulated yet" (|loss| is never negative)
EMA_UNSET = -1.0


def update_loss_ema(prev_ema, loss):
    """(traced) next EMA of |loss|: seeds from the first finite loss, holds
    its last finite value through non-finite steps (a NaN loss must not
    poison the reference the recovery will be judged against)."""
    loss32 = jnp.abs(loss.astype(jnp.float32))
    seeded = jnp.where(
        prev_ema < 0.0, loss32, EMA_DECAY * prev_ema + (1.0 - EMA_DECAY) * loss32
    )
    return jnp.where(jnp.isfinite(loss32), seeded, prev_ema)


def spike_score(loss, prev_ema):
    """(traced) |loss| / EMA(|loss|) against the PREVIOUS step's EMA — the
    score must compare against history the current step has not already
    dragged upward.  1.0 while the EMA is unset; ``inf`` for non-finite
    loss (so one threshold covers both divergence modes)."""
    loss32 = jnp.abs(loss.astype(jnp.float32))
    ref = jnp.maximum(prev_ema, jnp.float32(1e-8))
    score = jnp.where(prev_ema < 0.0, jnp.float32(1.0), loss32 / ref)
    return jnp.where(jnp.isfinite(loss32), score, jnp.float32(jnp.inf))


def probe_metrics(total_loss, update_norm, spike, worker_nan_rows):
    """The probe sub-dictionary both engines nest under ``PROBE_KEY``."""
    return {
        "loss_finite": jnp.isfinite(total_loss).astype(jnp.int32),
        "update_norm": update_norm,
        "spike": spike,
        "worker_nan_rows": worker_nan_rows.astype(jnp.int32),
    }


def host_view(metrics):
    """Host-side numpy view of one step dispatch's probe (or ``None`` when
    the engine ran with ``health_probe=False``).  Under ``--unroll`` the
    fields carry a leading K dim — exactly one entry per scanned step."""
    import jax
    import numpy as np

    if PROBE_KEY not in metrics:
        return None
    return {
        name: np.asarray(jax.device_get(value))
        for name, value in metrics[PROBE_KEY].items()
    }

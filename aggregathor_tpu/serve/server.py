"""Stdlib HTTP serving front end: ``/predict``, ``/healthz``, ``/metrics``.

A ``ThreadingHTTPServer`` (one handler thread per connection — the handler
threads only parse/serialize JSON and block on the micro-batcher ticket, so
the GIL is irrelevant: all compute happens in the batcher's single dispatch
thread, inside XLA) in front of :class:`serve.batcher.MicroBatcher` in front
of :class:`serve.engine.InferenceEngine`.

- ``POST /predict``  body ``{"inputs": [[...], ...]}`` (rows shaped like the
  experiment's ``sample_shape``, or flat row vectors of the same size) ->
  ``{"predictions": [...], "disagreement": [...], "bucket": B}``;
  ``429`` + ``{"error": "shed", ...}`` under load-shedding, ``400`` on
  malformed input.
- ``GET /healthz``   liveness + replica summary (suspect replicas flagged
  from the latest disagreement scores).
- ``GET /metrics``   the metrics surface, in two formats: the original JSON
  gauge snapshot (byte-compatible with the pre-registry payload — the smoke
  scripts parse it), and Prometheus text exposition via
  ``/metrics?format=prometheus`` or an ``Accept: text/plain`` header.  Both
  read the ONE process-wide registry (``obs/metrics.py``): request latency
  is a registry histogram, shed/served counts are registry counters, queue
  depth / occupancy / compile count are scrape-time gauge callbacks.

Observability flows through ``obs/summaries.SummaryWriter`` when a summary
directory is configured: one tagged ``serve_batch`` event per dispatched
batch and one ``serve_shed`` event per rejected request — the same JSONL
stream the training loop writes, so one tail follows both phases.  Span
tracing (``obs/trace.py``, when installed) brackets the request lifecycle:
``serve.request`` (handler) around ``serve.enqueue`` / ``serve.batch`` /
``serve.jit`` (batcher/engine).
"""

import json
import threading
import urllib.parse

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..obs import LatencyHistogram
from ..obs import metrics as obs_metrics
from ..obs import trace
from ..utils import UserException, info
from .batcher import LoadShed, MicroBatcher


class _Handler(BaseHTTPRequestHandler):
    server_version = "aggregathor-serve/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # the metrics endpoint replaces stderr chatter
        pass

    def _reply(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code, body, content_type):
        body = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _wants_prometheus(self, query):
        """Format negotiation: explicit ``?format=`` wins; otherwise an
        ``Accept`` header that asks for text/plain (and not JSON) —
        Prometheus scrapers send ``text/plain;version=0.0.4``."""
        fmt = urllib.parse.parse_qs(query).get("format", [None])[0]
        if fmt is not None:
            if fmt not in ("json", "prometheus"):
                raise UserException(
                    "unknown metrics format %r (json or prometheus)" % fmt
                )
            return fmt == "prometheus"
        accept = self.headers.get("Accept", "")
        return "text/plain" in accept and "application/json" not in accept

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        if parsed.path == "/healthz":
            self._reply(200, self.server.health_payload())
        elif parsed.path == "/metrics":
            try:
                prometheus = self._wants_prometheus(parsed.query)
            except UserException as exc:
                self._reply(400, {"error": str(exc)})
                return
            if prometheus:
                self._reply_text(200, self.server.prometheus_payload(),
                                 obs_metrics.PROMETHEUS_CONTENT_TYPE)
            else:
                self._reply(200, self.server.metrics_payload())
        else:
            self._reply(404, {"error": "unknown path %r" % self.path})

    def do_POST(self):
        with trace.span("serve.request", cat="serve"):
            self._do_predict()

    def _do_predict(self):
        # Drain the body FIRST, before any reply: under HTTP/1.1 keep-alive
        # an unread body would be parsed as the next request line, desyncing
        # the connection for whatever the client sends next.
        body = self.rfile.read(int(self.headers.get("Content-Length", "0")))
        if self.path != "/predict":
            self._reply(404, {"error": "unknown path %r" % self.path})
            return
        started = self.server.clock()
        try:
            request = json.loads(body or b"{}")
            rows = self.server.parse_inputs(request)
        except (ValueError, TypeError, UserException) as exc:
            self._reply(400, {"error": str(exc)})
            return
        try:
            ticket = self.server.batcher.submit(rows)
        except LoadShed as exc:
            self.server.note_shed(rows.shape[0], str(exc))
            self._reply(429, {"error": "shed", "detail": str(exc)})
            return
        except (ValueError, RuntimeError) as exc:
            self._reply(400, {"error": str(exc)})
            return
        try:
            result = ticket.wait(self.server.request_timeout_s)
        except TimeoutError as exc:
            self._reply(504, {"error": str(exc)})
            return
        except Exception as exc:  # inference failure: surfaced, server lives
            self._reply(500, {"error": str(exc)})
            return
        self.server.latency.record(self.server.clock() - started)
        self._reply(200, {
            "predictions": [int(p) for p in result["predictions"]],
            "disagreement": [_jsonable(v) for v in np.atleast_1d(result["disagreement"])],
            "bucket": int(result["bucket"]),
        })


def _jsonable(value):
    value = float(value)
    return value if np.isfinite(value) else None  # strict JSON: inf/NaN -> null


class InferenceServer(ThreadingHTTPServer):
    """The serving process: HTTP front end + micro-batcher + engine.

    ``port=0`` binds an ephemeral port (read ``server_address[1]`` after
    construction — the smoke script's ready-file does).  ``summaries`` is an
    optional ``SummaryWriter``; ``flag_threshold`` marks a replica suspect
    when its latest disagreement exceeds it (non-finite scores are always
    suspect).  ``registry`` is the metrics registry to export through
    (default: the process-wide ``obs.metrics.REGISTRY``).  CONCURRENT
    servers sharing one registry share its serve_* instruments;
    ``shutdown_all`` unregisters them, so a SUCCESSOR server starts from
    fresh counts (and the scrape-time gauge closures stop pinning this
    server's engine — its replica buffers become collectable).
    """

    daemon_threads = True

    def __init__(self, engine, host="127.0.0.1", port=0, max_latency_s=0.010,
                 queue_bound=256, summaries=None, request_timeout_s=60.0,
                 flag_threshold=None, clock=None, registry=None,
                 custody_verified=None):
        import time

        super().__init__((host, int(port)), _Handler)
        self.engine = engine
        # Chain-of-custody verdict of the served checkpoints (cli/serve.py):
        # True = every replica's lineage manifest verified, False = at least
        # one unsigned/unverified restore was explicitly allowed through,
        # None = no --session-secret (verification not attempted).  Updated
        # on hot restore (set_custody_verified), surfaced by /healthz.
        self.custody_verified = custody_verified
        self.clock = clock if clock is not None else time.monotonic
        self.summaries = summaries
        self.request_timeout_s = float(request_timeout_s)
        self.flag_threshold = flag_threshold
        self.registry = registry if registry is not None else obs_metrics.REGISTRY
        self._metric_names = [
            "serve_request_latency_seconds", "serve_shed_requests_total",
            "serve_shed_rows_total", "serve_batches_total",
            "serve_served_rows_total", "serve_replica_disagreement",
            "serve_queue_rows", "serve_queue_bound", "serve_compile_count",
            "serve_batch_occupancy_fill", "serve_suspect_replica_count",
        ]
        # Registry-backed instruments; ``latency`` keeps the LatencyHistogram
        # API (record/percentiles/count), so the JSON payload is unchanged.
        self.latency = self.registry.histogram(
            "serve_request_latency_seconds", "End-to-end /predict latency"
        )
        self._m_shed_requests = self.registry.counter(
            "serve_shed_requests_total", "Requests rejected by load-shedding (429)"
        )
        self._m_shed_rows = self.registry.counter(
            "serve_shed_rows_total", "Rows rejected by load-shedding"
        )
        self._m_batches = self.registry.counter(
            "serve_batches_total", "Micro-batches dispatched"
        )
        self._m_served_rows = self.registry.counter(
            "serve_served_rows_total", "Rows served through dispatched batches"
        )
        self._m_disagreement = self.registry.gauge(
            "serve_replica_disagreement",
            "Latest per-replica disagreement score", labelnames=("replica",),
        )
        self.shed_rows = 0
        self._event_lock = threading.Lock()
        self._last_disagreement = [0.0] * engine.nb_replicas
        self.batcher = MicroBatcher(
            engine.predict,
            max_latency_s=max_latency_s,
            max_batch=engine.buckets[-1],
            queue_bound=queue_bound,
            on_batch=self._on_batch,
        )
        # Live views, read at scrape time (no writer loop to go stale).
        self.registry.gauge(
            "serve_queue_rows", "Rows queued awaiting dispatch"
        ).set_function(lambda: self.batcher.queue_depth)
        self.registry.gauge(
            "serve_queue_bound", "Queued-row bound beyond which requests shed"
        ).set_function(lambda: self.batcher.queue_bound)
        self.registry.gauge(
            "serve_compile_count", "Executables compiled (one per bucket shape)"
        ).set_function(lambda: self.engine.compile_count)
        self.registry.gauge(
            "serve_batch_occupancy_fill", "Row fill of the last dispatched batch"
        ).set_function(
            lambda: (self.batcher.last_occupancy[0] / self.batcher.last_occupancy[1])
            if self.batcher.last_occupancy[1] else 0.0
        )
        self.registry.gauge(
            "serve_suspect_replica_count", "Replicas currently flagged suspect"
        ).set_function(lambda: len(self.suspect_replicas()))
        self._serve_thread = None

    # ------------------------------------------------------------------ #
    # request plumbing

    def parse_inputs(self, request):
        """``{"inputs": [...]}`` -> (k, *sample_shape) float32 rows.  Rows may
        arrive shaped or flattened; both forms are reshaped and validated
        against the experiment's sample shape."""
        inputs = request.get("inputs")
        if inputs is None:
            raise UserException('Request body wants {"inputs": [[...], ...]}')
        rows = np.asarray(inputs, np.float32)
        shape = self.engine.sample_shape
        if rows.ndim == 1:  # one flat sample
            rows = rows[None]
        if rows.ndim == 2 and rows.shape[1] == int(np.prod(shape)):
            rows = rows.reshape((rows.shape[0],) + shape)
        if rows.ndim == len(shape):  # one shaped sample
            rows = rows[None]
        if rows.ndim != len(shape) + 1 or tuple(rows.shape[1:]) != shape:
            raise UserException(
                "Input rows of shape %r do not match sample shape %r (flat %d also accepted)"
                % (tuple(rows.shape[1:]), shape, int(np.prod(shape)))
            )
        return rows

    def _on_batch(self, rows, requests, latency_s, output):
        disagreement = np.atleast_1d(np.asarray(output.get("disagreement", [])))
        self._m_batches.inc()
        self._m_served_rows.inc(int(rows))
        with self._event_lock:
            if disagreement.size == self.engine.nb_replicas:
                self._last_disagreement = [float(v) for v in disagreement]
                for index, score in enumerate(self._last_disagreement):
                    self._m_disagreement.labels(replica=str(index)).set(
                        score if np.isfinite(score) else float("inf")
                    )
        if self.summaries is not None:
            self.summaries.event(self.batcher.batch_count, "serve_batch", {
                "rows": int(rows),
                "requests": int(requests),
                "bucket": int(output.get("bucket", 0)),
                "batch_latency_ms": float(latency_s) * 1e3,
                "disagreement": [_jsonable(v) for v in disagreement],
            })

    def note_shed(self, rows, detail):
        self._m_shed_requests.inc()
        self._m_shed_rows.inc(int(rows))
        with self._event_lock:
            self.shed_rows += int(rows)
        if self.summaries is not None:
            self.summaries.event(self.batcher.batch_count, "serve_shed", {
                "rows": int(rows),
                "queue_depth": self.batcher.queue_depth,
                "detail": detail,
            })

    # ------------------------------------------------------------------ #
    # introspection payloads

    def suspect_replicas(self):
        """Replica indices whose latest disagreement flags them: non-finite
        always; above ``flag_threshold`` when one is configured."""
        with self._event_lock:
            scores = list(self._last_disagreement)
        suspects = []
        for index, score in enumerate(scores):
            if not np.isfinite(score):
                suspects.append(index)
            elif self.flag_threshold is not None and score > self.flag_threshold:
                suspects.append(index)
        return suspects

    def set_custody_verified(self, verdict):
        """Update the provenance verdict after a hot restore."""
        self.custody_verified = verdict

    def health_payload(self):
        return {
            "status": "ok",
            "replicas": self.engine.nb_replicas,
            "vote": type(self.engine.gar).__name__ if self.engine.gar else None,
            "buckets": list(self.engine.buckets),
            "suspect_replicas": self.suspect_replicas(),
            "custody_verified": self.custody_verified,
        }

    def metrics_payload(self):
        tail = self.latency.percentiles()
        occupancy_rows, occupancy_cap = self.batcher.last_occupancy
        with self._event_lock:
            disagreement = [_jsonable(v) for v in self._last_disagreement]
            shed_rows = self.shed_rows
        return {
            "queue_depth": self.batcher.queue_depth,
            "queue_bound": self.batcher.queue_bound,
            "batch_count": self.batcher.batch_count,
            "served_rows": self.batcher.served_rows,
            "shed_count": self.batcher.shed_count,
            "shed_rows": shed_rows,
            "batch_occupancy": {
                "rows": occupancy_rows, "cap": occupancy_cap,
                "fill": (occupancy_rows / occupancy_cap) if occupancy_cap else 0.0,
            },
            "latency_ms": {
                name: (tail[name] * 1e3 if tail else None)
                for name, _ in LatencyHistogram.POINTS
            },
            "request_count": self.latency.count,
            "per_replica_disagreement": disagreement,
            "suspect_replicas": self.suspect_replicas(),
            "compile_count": self.engine.compile_count,
            "nb_buckets": len(self.engine.buckets),
        }

    def prometheus_payload(self):
        """Text exposition of the whole registry (``/metrics?format=
        prometheus``) — training/serve metrics that share the process-wide
        registry scrape together."""
        return self.registry.render_prometheus()

    # ------------------------------------------------------------------ #
    # lifecycle

    def serve_background(self):
        """Run ``serve_forever`` on a daemon thread; returns (host, port)."""
        self._serve_thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="serve-http"
        )
        self._serve_thread.start()
        host, port = self.server_address[:2]
        info("Serving on http://%s:%d (replicas=%d, vote=%s, buckets=%r)"
             % (host, port, self.engine.nb_replicas,
                type(self.engine.gar).__name__ if self.engine.gar else "none",
                list(self.engine.buckets)))
        return host, port

    def shutdown_all(self):
        """Stop the HTTP loop and the batcher (idempotent), and unregister
        this server's serve_* instruments so a successor starts fresh and
        the gauge closures no longer keep the engine alive."""
        self.shutdown()
        self.server_close()
        self.batcher.close()
        if self._serve_thread is not None:
            self._serve_thread.join(5.0)
            self._serve_thread = None
        for name in self._metric_names:
            self.registry.unregister(name)

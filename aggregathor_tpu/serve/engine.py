"""Compiled inference engine: bucket-ladder batching + replicated robust vote.

The serving counterpart of ``parallel/engine.py``: one jitted apply path per
*bucket shape*, never per request.  Incoming batches are padded up to a fixed
ladder of power-of-two bucket sizes, so after a warmup pass over the ladder
steady-state serving triggers **zero recompiles** — the same discipline as
chaos' zero-recompile regime scheduler, asserted the same way (the jit cache
size is the compile count, ``compile_count``).

Byzantine robustness transfers from training to serving: with ``R`` replica
parameter sets (distinct checkpoints, or copies of one), every bucket runs
through all R replicas (``vmap`` over a stacked leading axis) and the
``(R, batch, classes)`` replica logits are reduced by a coordinate-wise GAR
(``gars/``) exactly as the training engine reduces the ``(n, d)`` gradient
matrix — replicas are workers, logit coordinates are gradient coordinates.
The NaN-last ordering convention carries over verbatim: a crashed replica
whose logits read NaN is absorbed by ``median`` (R >= 2f+1 replicas mask f
faulty ones), while plain ``average`` is poisoned — the serving-side
restatement of the AggregaThor thesis.  Per-replica **disagreement scores**
(mean squared deviation from the voted logits over the valid rows; non-finite
deviations read +inf) are surfaced per batch for quarantine-style flagging.

Two serving-scale levers ride the SAME compiled executables (both are
traced operands, so neither ever recompiles a bucket — the serve/ v2
zero-recompile contract, asserted by tests/test_serve.py):

- **Active-replica mask** (``set_active_replicas``): a retired replica's
  logits are masked to NaN BEFORE the vote, so it is excluded exactly like
  a crashed worker — and exactly like one it SPENDS the vote's declared-f
  budget, which is why the autoscaler (``serve/autoscale.py``) owns the
  feasibility floor ``retired + fault reserve <= f``.  Whether the rule
  actually absorbs that many dead rows is PROBED (``vote_absorbs_retired``),
  not trusted from a flag.
- **Hot weight swap** (``swap_replicas``): the ``(params, active, step)``
  triple is ONE atomically-rebound tuple — an in-flight forward finishes
  on the old stack, the next dispatch reads the new one, and every
  ``predict`` reports the ``weights_step`` its batch actually ran on (the
  zero-downtime weight pipeline's wrong-weight check keys on it,
  ``serve/weights.py``).
"""

import threading
import warnings

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import trace
from ..utils import UserException, info


def _quiet_dispatch(fn, *args):
    """Call the jitted forward with the 'donated buffers were not usable'
    UserWarning silenced: the padded input is donated for the TPU path
    (where logits can alias its pages); XLA:CPU declines the donation and
    would otherwise warn once per bucket shape, per process."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        return fn(*args)


def bucket_ladder(max_batch, min_bucket=1):
    """The power-of-two bucket ladder covering batch sizes up to ``max_batch``.

    ``(min_bucket, 2*min_bucket, ..., max_batch)`` — ``max_batch`` is rounded
    UP to the next power of two so every request size <= max_batch has a
    bucket.  A fixed ladder bounds the compile count at ``log2(max_batch)``
    executables while wasting at most half of any bucket's rows on padding.
    """
    max_batch, min_bucket = int(max_batch), int(min_bucket)
    if max_batch < 1 or min_bucket < 1:
        raise UserException(
            "bucket ladder wants positive sizes (max_batch=%d, min_bucket=%d)"
            % (max_batch, min_bucket)
        )
    ladder = []
    size = 1
    while size < min_bucket:
        size *= 2
    while True:
        ladder.append(size)
        if size >= max_batch:
            return tuple(ladder)
        size *= 2


def choose_bucket(nb_rows, buckets):
    """Smallest bucket holding ``nb_rows`` rows, or None when none fits.

    ``buckets`` must be sorted ascending (``InferenceEngine`` guarantees it).
    """
    for bucket in buckets:
        if bucket >= nb_rows:
            return bucket
    return None


def restore_params(experiment, directory, tx, step=None, seed=0,
                   base_name=None, authenticator=None, cipher=None,
                   allow_legacy_tags=True, custody=None):
    """Restore a trained checkpoint's parameters for serving.

    Deserializes into a freshly-initialized host-side :class:`TrainState`
    template (so shape/dtype mismatches fail loudly, same restore discipline
    as training) and returns ``(params, step)``.  ``tx`` must match the
    optimizer the checkpoint was trained with — the snapshot serializes the
    optimizer state, and a mismatched treedef fails at deserialization
    instead of silently seeding garbage.  ``authenticator``/``cipher`` honor
    the training-side checkpoint authentication and at-rest encryption
    (``obs/checkpoint.py``); ``custody`` (a
    ``secure.custody.ChainOfCustody``) additionally verifies the signed
    lineage manifest before loading — the serving end of the
    train -> sign -> serve chain (docs/security.md).
    """
    from .. import config
    from ..core.train_state import TrainState
    from ..obs import Checkpoints

    params = experiment.init(jax.random.PRNGKey(seed))
    template = jax.device_get(
        TrainState.create(params, tx, rng=jax.random.PRNGKey(seed))
    )
    checkpoints = Checkpoints(
        directory,
        base_name if base_name is not None else config.default_checkpoint_base_name,
        authenticator=authenticator,
        cipher=cipher,
        allow_legacy_tags=allow_legacy_tags,
        custody=custody,
    )
    state, at_step = checkpoints.restore(template, step=step)
    return state.params, at_step


class InferenceEngine:
    """Checkpoint-to-predictions apply path with R-way robust replication.

    Args:
      experiment: a ``models`` Experiment instance — ``predict_logits`` is
        the apply path that gets jitted; ``sample_shape`` validates inputs.
      replicas: list of R parameter pytrees (R >= 1).  All replicas must
        share one treedef/shape (copies or same-topology checkpoints).
      gar: a ``gars`` GAR *instance* over ``nb_workers == R`` (or None for
        single-replica serving / plain first-replica logits).  Coordinate-
        wise rules (median, average-nan, trimmed-mean) are the natural fit;
        any registered rule whose (n, f) check admits R replicas works.
      max_batch: largest servable batch; also the ladder top when
        ``buckets`` is not given.
      buckets: explicit bucket ladder (sorted ascending after normalization);
        default ``bucket_ladder(max_batch)``.
      seed: key for randomized meta-rules (``uses_key`` GARs draw a FIXED
        per-engine key — serving is deterministic, unlike training's
        per-step re-draw).

    The padded input buffer is donated to the jit — it is rebuilt per call,
    so the device may reuse its pages for the logits.
    """

    def __init__(self, experiment, replicas, gar=None, max_batch=64,
                 buckets=None, seed=0, weights_step=None):
        if not replicas:
            raise UserException("InferenceEngine needs at least one replica")
        self.experiment = experiment
        self.nb_replicas = len(replicas)
        self.gar = gar
        if gar is not None and gar.nb_workers != self.nb_replicas:
            raise UserException(
                "GAR %s aggregates %d workers but %d replicas are loaded"
                % (type(gar).__name__, gar.nb_workers, self.nb_replicas)
            )
        self.buckets = tuple(sorted(set(
            int(b) for b in (buckets if buckets else bucket_ladder(max_batch))
        )))
        if not self.buckets or self.buckets[0] < 1:
            raise UserException("Bucket ladder must hold positive sizes: %r" % (self.buckets,))
        self.sample_shape = tuple(experiment.sample_shape)
        self._vote_key = jax.random.PRNGKey(seed)
        # The live serving state is ONE tuple — (stacked params, active
        # mask, weights step) — rebound atomically by swap_replicas /
        # set_active_replicas, so a dispatch never reads a torn mix of old
        # weights with a new step tag.  READS are lock-free (tuple rebind
        # is atomic); the two MUTATORS are read-modify-writes and hold
        # _live_lock so a concurrent hot swap (watcher/SIGHUP thread) and
        # autoscale move cannot silently undo each other's update.
        self._live_lock = threading.Lock()
        self._live = (
            self._stack(replicas),
            jnp.ones((self.nb_replicas,), jnp.bool_),
            weights_step,
        )
        apply_fn = experiment.predict_logits

        def forward(params_stack, x, nb_valid, key, active):
            logits = jax.vmap(apply_fn, in_axes=(0, None))(params_stack, x)
            logits = logits.astype(jnp.float32)  # GAR math in f32, like training
            nb_r, bucket = logits.shape[0], logits.shape[1]
            flat = logits.reshape((nb_r, -1))
            # A retired replica is a crashed one as far as the vote can
            # tell: its row reads NaN and the NaN-last convention excludes
            # it (nan_row_tolerant rules only — set_active_replicas
            # enforces that).  ``active`` is a traced operand: scaling the
            # pool never touches the compiled ladder.
            flat = jnp.where(active[:, None], flat, jnp.nan)
            if self.gar is None or nb_r == 1:
                voted = flat[0]
            else:
                voted = self.gar.aggregate(flat, key=key)
            # Disagreement over the VALID rows only: padding rows are zeros,
            # whose logits would dilute (never inflate) a faulty replica's
            # score.  Non-finite deviation = maximal disagreement (+inf), so
            # a NaN replica is flagged, not averaged away; a RETIRED replica
            # reads NaN (not +inf) so the host can tell "scaled out" from
            # "suspect".
            row_valid = jax.lax.broadcasted_iota(jnp.int32, (bucket,), 0) < nb_valid
            coord_valid = jnp.repeat(row_valid, flat.shape[1] // bucket)
            deviation = (flat - voted[None, :]) ** 2
            deviation = jnp.where(jnp.isfinite(deviation), deviation, jnp.inf)
            masked = jnp.where(coord_valid[None, :], deviation, 0.0)
            denom = jnp.maximum(nb_valid * (flat.shape[1] // bucket), 1).astype(jnp.float32)
            disagreement = jnp.sum(masked, axis=1) / denom
            disagreement = jnp.where(active, disagreement, jnp.nan)
            voted = voted.reshape(logits.shape[1:])
            return jnp.argmax(voted, axis=-1), voted, disagreement

        self._fn = jax.jit(forward, donate_argnums=(1,))

    @staticmethod
    def _stack(replicas):
        # One stacked (R, ...) pytree: vmap's in_axes=0 runs every replica
        # through the same compiled forward — R is a *shape*, not a loop.
        return jax.device_put(jax.tree_util.tree_map(
            lambda *leaves: jnp.stack([jnp.asarray(l) for l in leaves]), *replicas
        ))

    @property
    def weights_step(self):
        """The training step of the currently-served weights (None when
        the source checkpoint did not carry one)."""
        return self._live[2]

    @property
    def active_replicas(self):
        """Sorted indices of the replicas currently voting."""
        mask = np.asarray(self._live[1])
        return [int(i) for i in np.nonzero(mask)[0]]

    def set_active_replicas(self, indices):
        """Scale the voting pool: serve with exactly ``indices`` active.

        Retired replicas' logits read NaN and are excluded by the vote —
        spending the declared-f budget exactly like a crashed replica, so
        the caller (``serve/autoscale.py``) must keep
        ``retired + expected faults <= f``.  The mask is a traced operand:
        ZERO recompiles at any pool size.  Returns the active list.
        """
        indices = sorted(set(int(i) for i in indices))
        if not indices:
            raise UserException("at least one replica must stay active")
        if indices[0] < 0 or indices[-1] >= self.nb_replicas:
            raise UserException(
                "active replicas %r out of range for R=%d"
                % (indices, self.nb_replicas)
            )
        if len(indices) < self.nb_replicas:
            if self.gar is None or self.nb_replicas == 1:
                raise UserException(
                    "cannot retire replicas without a vote rule: the "
                    "single/unvoted forward serves replica 0 unconditionally"
                )
            if not self.vote_absorbs_retired(self.nb_replicas - len(indices)):
                raise UserException(
                    "vote rule %s does not absorb %d retired (NaN) replica "
                    "row(s) at R=%d: the vote would be poisoned — retire "
                    "fewer replicas or declare a larger f"
                    % (type(self.gar).__name__,
                       self.nb_replicas - len(indices), self.nb_replicas)
                )
        mask = np.zeros((self.nb_replicas,), bool)
        mask[indices] = True
        with self._live_lock:
            stack, _, step = self._live
            self._live = (stack, jnp.asarray(mask), step)
        return indices

    def vote_absorbs_retired(self, nb_retired):
        """Concrete feasibility probe: does the vote rule return a finite
        aggregate with ``nb_retired`` all-NaN rows in the stack?  Retired
        replicas are NaN rows, and each rule's real absorption boundary
        (median's order-statistic slots, krum's +inf distances,
        average-nan's exclusion, plain average's none) is probed rather
        than trusted from a flag — the same reject-by-measurement
        discipline as the graftcheck GAR contract checker
        (docs/analysis.md).  The probe runs the rule eagerly on a tiny
        host matrix; it never touches the bucket executables."""
        if self.gar is None:
            return nb_retired == 0
        probe = np.ones((self.nb_replicas, 4), np.float32)
        if nb_retired > 0:
            probe[self.nb_replicas - nb_retired:] = np.nan
        try:
            voted = self.gar.aggregate(jnp.asarray(probe), key=self._vote_key)
        except Exception:
            return False
        return bool(np.isfinite(np.asarray(voted)).all())

    def swap_replicas(self, replicas, step=None):
        """Hot weight swap: replace the replica parameter stack in place.

        The new replicas must match the serving topology (same count, same
        treedef, same leaf shapes/dtypes) so every already-compiled bucket
        executable keeps serving — a swap costs one host->device transfer
        and ZERO recompiles.  The live-tuple assignment is an atomic
        reference swap: an in-flight forward finishes on the old stack, the
        next dispatch reads the new one (and reports the new ``step`` as
        its ``weights_step`` — never a torn pairing).  The active-replica
        mask survives the swap.  Used by the checkpoint watcher
        (``serve/weights.py``) and the serve CLI's SIGHUP hot restore after
        custody verification (docs/security.md).
        """
        if len(replicas) != self.nb_replicas:
            raise UserException(
                "swap_replicas got %d replica(s) for a %d-replica engine "
                "(the vote rule and compiled forwards are sized R=%d)"
                % (len(replicas), self.nb_replicas, self.nb_replicas)
            )
        fresh = self._stack(replicas)
        old = jax.tree_util.tree_leaves(self._live[0])
        new = jax.tree_util.tree_leaves(fresh)
        if len(old) != len(new) or any(
            (a.shape, a.dtype) != (b.shape, b.dtype) for a, b in zip(old, new)
        ):
            raise UserException(
                "swap_replicas: the new checkpoints do not match the serving "
                "topology (leaf shape/dtype mismatch) — restart to change it"
            )
        with self._live_lock:
            self._live = (fresh, self._live[1], step)
        return self.compile_count

    @property
    def compile_count(self):
        """Executables compiled so far — one per distinct bucket shape.  The
        zero-recompile contract: after ``warmup()`` this equals
        ``len(self.buckets)`` and never grows in steady state (asserted by
        tests/test_serve.py)."""
        return int(self._fn._cache_size())

    def warmup(self):
        """Compile every ladder bucket up front (zeros input), so the first
        real request never pays a compile.  Returns the compile count."""
        stack, active, _ = self._live
        for bucket in self.buckets:
            pad = jnp.zeros((bucket,) + self.sample_shape, jnp.float32)
            jax.block_until_ready(_quiet_dispatch(
                self._fn, stack, pad, jnp.int32(bucket), self._vote_key, active
            ))
        info(
            "Inference warmup: %d bucket(s) %r compiled, %d replica(s), vote=%s"
            % (len(self.buckets), list(self.buckets), self.nb_replicas,
               type(self.gar).__name__ if self.gar else "none")
        )
        return self.compile_count

    def _run_bucket(self, rows, live):
        stack, active, _ = live
        bucket = choose_bucket(rows.shape[0], self.buckets)
        # Pad HOST-side: one array and one host->device transfer per call,
        # instead of a device zeros allocation plus a scatter update — the
        # padding cost matters at the small buckets where it dominates the
        # forward.  The transferred buffer is the donated jit argument.
        pad = np.zeros((bucket,) + self.sample_shape, np.float32)
        pad[: rows.shape[0]] = rows
        # One span covers dispatch AND the result fetch: under async
        # dispatch the device_get is where the forward's wall time lands.
        with trace.span("serve.jit", cat="serve", bucket=int(bucket),
                        rows=int(rows.shape[0])):
            preds, logits, disagreement = _quiet_dispatch(
                self._fn, stack, jnp.asarray(pad), jnp.int32(rows.shape[0]),
                self._vote_key, active,
            )
            n = rows.shape[0]
            return (
                np.asarray(jax.device_get(preds))[:n],
                np.asarray(jax.device_get(logits))[:n],
                np.asarray(jax.device_get(disagreement)),
                bucket,
            )

    def predict(self, x):
        """Serve a batch: ``(n, *sample_shape)`` -> dict with ``predictions``
        (n,) int labels, ``logits`` (n, classes) voted logits,
        ``disagreement`` (R,) per-replica scores (rows-weighted over chunks;
        NaN = retired replica), ``bucket`` (the last bucket used),
        ``weights_step`` (the checkpoint step this batch served from) and
        ``active_replicas``.  Requests beyond the ladder top are chunked at
        the largest bucket.
        """
        x = np.asarray(x, np.float32)
        if x.ndim == len(self.sample_shape):  # single sample convenience
            x = x[None]
        if tuple(x.shape[1:]) != self.sample_shape:
            raise UserException(
                "Input shape %r does not match the experiment's sample shape %r"
                % (tuple(x.shape[1:]), self.sample_shape)
            )
        if x.shape[0] == 0:
            raise UserException("Empty inference batch")
        # ONE read of the live tuple per predict: every chunk of this batch
        # serves the same weights, and the reported weights_step can never
        # pair old weights with a new step tag (the hot-swap atomicity the
        # load benchmark's wrong-weight check leans on).
        live = self._live
        top = self.buckets[-1]
        preds, logits, scores, weights, bucket = [], [], [], [], None
        for start in range(0, x.shape[0], top):
            chunk = x[start:start + top]
            p, l, d, bucket = self._run_bucket(chunk, live)
            preds.append(p)
            logits.append(l)
            scores.append(d)
            weights.append(chunk.shape[0])
        total = float(sum(weights))
        disagreement = sum(s * (w / total) for s, w in zip(scores, weights))
        active = np.asarray(live[1])
        return {
            "predictions": np.concatenate(preds),
            "logits": np.concatenate(logits),
            "disagreement": np.asarray(disagreement),
            "bucket": bucket,
            "weights_step": live[2],
            "active_replicas": [int(i) for i in np.nonzero(active)[0]],
        }

"""Registry-driven serving autoscaler: pressure in, pool decisions out.

The serving pool has two scalable axes, and this module drives both from
the SAME live signals — queue depth (``serve_queue_rows``), the request
p99 (the ``serve_request_latency_seconds`` reservoir) and the shed rate
(``serve_shed_requests_total`` deltas), all read off the one process-wide
metrics registry (``obs/metrics.py``) rather than private scheduler state,
so whatever a Prometheus scrape sees is exactly what the autoscaler acted
on:

- **dispatch lanes** (``ContinuousBatcher.set_lanes``): concurrent
  in-flight batches over the SHARED compiled bucket ladder — the cheap
  capacity lever, zero recompiles at any lane count;
- **vote replicas** (``InferenceEngine.set_active_replicas``): under
  pressure that out-lasts the lane ceiling, redundancy is traded for
  capacity by RETIRING replicas from the vote (most-suspect first, so a
  flagged replica is the first to go).  A retired replica is a NaN row to
  the vote and therefore SPENDS the declared-f budget — which is why the
  pool floor is a feasibility statement, not a knob: at most
  ``f - fault_reserve`` replicas may ever be retired (``fault_reserve``
  keeps budget for real faults, e.g. the poisoned replica the load
  benchmark serves through), and each depth is additionally PROBED against
  the actual rule (``InferenceEngine.vote_absorbs_retired``).  Calm
  re-admits replicas BEFORE dropping lanes: redundancy is restored first.
  (On accelerator deployments each replica forward is real compute to
  release; on this vmapped reproduction the saving is semantic — the
  lever is kept exact so the feasibility math, not the speedup, is what
  the tests pin.)

Both axes are flattened into one :class:`CapacityLadder` of rungs ordered
by capacity — ``(lanes 1..L, retired 0)`` then ``(L, retired 1..k)`` — and
a PURE hysteresis policy (:class:`AutoscalePolicy`, the
``parallel/deadline.py`` discipline: synthetic clock, no threads, pinned by
tests/test_serve_sched.py against synthetic traces) decides when to move:
sustained pressure for ``up-patience`` ticks climbs one rung, sustained
calm for ``down-patience`` ticks descends one, and every move opens a
``cooldown`` window so the controller cannot thrash.  The runtime
:class:`PoolAutoscaler` is the thin executor around it: sample, decide,
apply, and account (``serve_autoscale_*`` instruments, a tagged
``serve_autoscale`` summary event per move).
"""

import threading
import time

from ..obs import events
from ..obs import metrics as obs_metrics
from ..obs import trace
from ..utils import UserException, info, parse_keyval


class AutoscaleConfig:
    """Parsed ``--autoscale-args`` (key:value strings, like every registry).

    Keys: ``interval`` (seconds between ticks, default 1), ``high-queue`` /
    ``low-queue`` (queued rows), ``high-p99`` / ``low-p99`` (seconds),
    ``high-shed`` / ``low-shed`` (sheds/s), ``up-patience`` /
    ``down-patience`` (consecutive pressured/calm ticks before a move —
    scale up fast, down slowly), ``cooldown`` (seconds both directions are
    suppressed after a move), ``fault-reserve`` (declared-f budget slots
    NEVER spent on retirement — kept for real replica faults), ``min-lanes``
    (the lane floor calm may descend to)."""

    DEFAULTS = {
        "interval": 1.0,
        "high-queue": 64.0,
        "low-queue": 4.0,
        "high-p99": 0.5,
        "low-p99": 0.1,
        "high-shed": 0.5,
        "low-shed": 0.0,
        "up-patience": 2,
        "down-patience": 6,
        "cooldown": 3.0,
        "fault-reserve": 1,
        "min-lanes": 1,
    }

    def __init__(self, args=None):
        kv = parse_keyval(args or [], dict(self.DEFAULTS), strict=True)
        self.interval = float(kv["interval"])
        self.high_queue = float(kv["high-queue"])
        self.low_queue = float(kv["low-queue"])
        self.high_p99 = float(kv["high-p99"])
        self.low_p99 = float(kv["low-p99"])
        self.high_shed = float(kv["high-shed"])
        self.low_shed = float(kv["low-shed"])
        self.up_patience = int(kv["up-patience"])
        self.down_patience = int(kv["down-patience"])
        self.cooldown = float(kv["cooldown"])
        self.fault_reserve = int(kv["fault-reserve"])
        self.min_lanes = int(kv["min-lanes"])
        if self.interval <= 0.0:
            raise UserException("autoscale interval must be > 0 seconds")
        for high, low, name in (
            (self.high_queue, self.low_queue, "queue"),
            (self.high_p99, self.low_p99, "p99"),
            (self.high_shed, self.low_shed, "shed"),
        ):
            if low < 0.0 or high < low:
                raise UserException(
                    "autoscale %s watermarks want 0 <= low (%g) <= high (%g)"
                    % (name, low, high)
                )
        if self.up_patience < 1 or self.down_patience < 1:
            raise UserException("autoscale patience values must be >= 1")
        if self.cooldown < 0.0:
            raise UserException("autoscale cooldown must be >= 0 seconds")
        if self.fault_reserve < 0:
            raise UserException("autoscale fault-reserve must be >= 0")
        if self.min_lanes < 1:
            raise UserException("autoscale min-lanes must be >= 1")


class AutoscalePolicy:
    """Pure hysteresis controller: one observation per tick, a direction out.

    ``observe(now, queue_rows, p99_s, shed_rate)`` returns ``"expand"``
    (sustained pressure), ``"shrink"`` (sustained calm) or ``None``.
    Pressure is ANY watermark exceeded (queue > high-queue, p99 > high-p99,
    shed rate > high-shed); calm is EVERY signal at/below its low
    watermark; the band between resets both streaks (no decision ever
    forms inside the hysteresis gap).  An unmeasured p99 (no completed
    requests yet) counts as calm-compatible, never as pressure.  After a
    decision both streaks reset and a ``cooldown`` window suppresses the
    next move — the serving twin of the guardian's spike-cooldown
    (guardian/watchdog.py).  Deterministic in its inputs: no wall clock,
    no registry — the executor owns sampling.
    """

    def __init__(self, config):
        self.config = config
        self.pressure_streak = 0
        self.calm_streak = 0
        self.cooldown_until = -float("inf")
        self.last_reason = None

    def observe(self, now, queue_rows, p99_s, shed_rate):
        cfg = self.config
        pressured = (
            queue_rows > cfg.high_queue
            or (p99_s is not None and p99_s > cfg.high_p99)
            or shed_rate > cfg.high_shed
        )
        calm = (
            queue_rows <= cfg.low_queue
            and (p99_s is None or p99_s <= cfg.low_p99)
            and shed_rate <= cfg.low_shed
        )
        if pressured:
            self.pressure_streak += 1
            self.calm_streak = 0
        elif calm:
            self.calm_streak += 1
            self.pressure_streak = 0
        else:  # inside the hysteresis band: no opinion forms
            self.pressure_streak = 0
            self.calm_streak = 0
        if now < self.cooldown_until:
            return None
        if self.pressure_streak >= cfg.up_patience:
            self.last_reason = (
                "pressure sustained %d tick(s): queue=%g p99=%s shed/s=%g"
                % (self.pressure_streak, queue_rows,
                   "%.4g" % p99_s if p99_s is not None else "-", shed_rate)
            )
            self.pressure_streak = self.calm_streak = 0
            self.cooldown_until = now + cfg.cooldown
            return "expand"
        if self.calm_streak >= cfg.down_patience:
            self.last_reason = (
                "calm sustained %d tick(s): queue=%g p99=%s shed/s=%g"
                % (self.calm_streak, queue_rows,
                   "%.4g" % p99_s if p99_s is not None else "-", shed_rate)
            )
            self.pressure_streak = self.calm_streak = 0
            self.cooldown_until = now + cfg.cooldown
            return "shrink"
        return None


class CapacityLadder:
    """The ordered capacity rungs: lanes first, replica retirement last.

    ``rung(i) -> (lanes, nb_retired)``: indices ``0..L-min_lanes`` grow the
    lane pool from ``min_lanes`` to ``max_lanes`` with full redundancy;
    indices beyond retire ``1..max_retire`` replicas at the lane ceiling.
    ``max_retire`` IS the declared-f feasibility floor in ladder form —
    the constructor caller (:class:`PoolAutoscaler`) derives it from
    ``min(f - fault_reserve, deepest probed-absorbable retirement)``, so no
    rung that exists can ever overdraw the vote's budget.
    """

    def __init__(self, min_lanes, max_lanes, max_retire):
        min_lanes, max_lanes = int(min_lanes), int(max_lanes)
        max_retire = int(max_retire)
        if not 1 <= min_lanes <= max_lanes:
            raise UserException(
                "capacity ladder wants 1 <= min_lanes (%d) <= max_lanes (%d)"
                % (min_lanes, max_lanes)
            )
        if max_retire < 0:
            raise UserException("max_retire must be >= 0")
        self.rungs = tuple(
            [(lanes, 0) for lanes in range(min_lanes, max_lanes + 1)]
            + [(max_lanes, retired) for retired in range(1, max_retire + 1)]
        )

    def __len__(self):
        return len(self.rungs)

    def rung(self, index):
        return self.rungs[index]

    def index_of(self, lanes, nb_retired):
        """The rung matching a live (lanes, retired) state; the closest
        not-larger rung when the state was set out-of-band."""
        best = 0
        for index, (rung_lanes, rung_retired) in enumerate(self.rungs):
            if (rung_retired, rung_lanes) <= (int(nb_retired), int(lanes)):
                best = index
        return best


class PoolAutoscaler:
    """Samples the registry, runs the policy, applies rung moves.

    Args:
      server: the :class:`~.frontend.InferenceServer` composite (scheduler
        + engine + disagreement state).
      config: an :class:`AutoscaleConfig`.
      registry: metrics registry to SAMPLE from and account into (default
        the process-wide one — must be the registry the server exports
        through, or the autoscaler would act on someone else's signals).
      clock: injectable monotonic clock (tests drive ``tick`` with
        synthetic time; ``start`` uses it only for bookkeeping).

    ``tick()`` is one full sample->decide->apply cycle and is safe to call
    manually (tests, or a trainer-style loop); ``start()`` runs it every
    ``config.interval`` seconds on a daemon thread.
    """

    def __init__(self, server, config=None, registry=None, clock=None):
        self.server = server
        self.config = config if config is not None else AutoscaleConfig()
        self.registry = (registry if registry is not None
                         else obs_metrics.REGISTRY)
        self.clock = clock if clock is not None else time.monotonic
        self.policy = AutoscalePolicy(self.config)
        engine = server.engine
        scheduler = server.scheduler
        retirable = 0
        if engine.gar is not None and engine.nb_replicas > 1:
            budget = max(0, engine.gar.nb_byz_workers - self.config.fault_reserve)
            while (retirable < budget
                   and engine.vote_absorbs_retired(retirable + 1)):
                retirable += 1
        self.ladder = CapacityLadder(
            min(self.config.min_lanes, scheduler.max_lanes),
            scheduler.max_lanes, retirable,
        )
        self._lock = threading.Lock()
        self._rung = self.ladder.index_of(
            scheduler.nb_lanes, engine.nb_replicas - len(engine.active_replicas)
        )
        self._last_shed = None
        self._last_sample_at = None
        self._last_latency_count = None
        self._thread = None
        self._stop = threading.Event()
        self._metric_names = [
            "serve_autoscale_rung", "serve_autoscale_events_total",
            "serve_autoscale_at_ceiling", "serve_shed_rate",
        ]
        self._g_rung = self.registry.gauge(
            "serve_autoscale_rung", "Current capacity rung (0 = floor)"
        )
        self._g_rung.set(self._rung)
        self._g_ceiling = self.registry.gauge(
            "serve_autoscale_at_ceiling",
            "1 while pressure demands more capacity than the top rung "
            "(lanes maxed, retirement at the declared-f floor)",
        )
        self._c_events = self.registry.counter(
            "serve_autoscale_events_total", "Applied scale moves",
            labelnames=("direction",),
        )
        self._g_shed_rate = self.registry.gauge(
            "serve_shed_rate", "Sheds per second over the last autoscale tick"
        )

    # ------------------------------------------------------------------ #
    # sampling (registry in, one observation out)

    def sample(self, now):
        """(queue_rows, p99_s, shed_rate) read from the live registry.

        The latency reservoir is all-time, not windowed, so a tail spike
        decays only as new requests displace old samples — a STALE p99
        (no request completed since the last tick) is therefore reported
        as None (unmeasured: calm-compatible, never pressure), or an idle
        server would stay pinned at its last loaded reading forever.
        Queue depth and the per-tick shed-rate delta are the live
        pressure signals; the p99 watermark catches sustained slow
        serving under sustained traffic."""
        families = {f.name: f for f in self.registry.families()}
        queue = families.get("serve_queue_rows")
        queue_rows = float(queue.value) if queue is not None else 0.0
        latency = families.get("serve_request_latency_seconds")
        tail = latency.percentiles() if latency is not None else None
        count = int(latency.count) if latency is not None else 0
        shed = families.get("serve_shed_requests_total")
        shed_total = float(shed.value) if shed is not None else 0.0
        with self._lock:
            last_shed, last_at = self._last_shed, self._last_sample_at
            last_count = self._last_latency_count
            self._last_shed, self._last_sample_at = shed_total, now
            self._last_latency_count = count
        fresh = last_count is None or count > last_count
        p99_s = float(tail["p99"]) if (tail and fresh) else None
        if last_shed is None or last_at is None or now <= last_at:
            shed_rate = 0.0
        else:
            shed_rate = max(0.0, shed_total - last_shed) / (now - last_at)
        self._g_shed_rate.set(shed_rate)
        return queue_rows, p99_s, shed_rate

    # ------------------------------------------------------------------ #
    # decide + apply

    @property
    def rung(self):
        with self._lock:
            return self._rung

    def tick(self, now=None):
        """One sample->decide->apply cycle; returns the applied direction
        (``"expand"``/``"shrink"``) or None."""
        now = self.clock() if now is None else now
        queue_rows, p99_s, shed_rate = self.sample(now)
        decision = self.policy.observe(now, queue_rows, p99_s, shed_rate)
        with self._lock:
            rung = self._rung
        at_ceiling = rung >= len(self.ladder) - 1
        wants_more = decision == "expand" or self.policy.pressure_streak > 0
        self._g_ceiling.set(1.0 if (at_ceiling and wants_more) else 0.0)
        if decision is None:
            return None
        target = rung + (1 if decision == "expand" else -1)
        target = max(0, min(len(self.ladder) - 1, target))
        if target == rung:
            return None  # pinned at the floor/ceiling: nothing to apply
        self._apply(target, decision, now)
        return decision

    def _apply(self, target, direction, now):
        lanes, nb_retired = self.ladder.rung(target)
        engine = self.server.engine
        keep = self._retirement_plan(nb_retired)
        engine.set_active_replicas(keep)
        self.server.scheduler.set_lanes(lanes)
        with self._lock:
            self._rung = target
        self._g_rung.set(target)
        self._c_events.labels(direction=direction).inc()
        trace.instant("serve.autoscale", cat="serve", direction=direction,
                      rung=int(target), lanes=int(lanes),
                      retired=int(nb_retired))
        events.emit("serve_autoscale",
                    step=self.server.scheduler.batch_count,
                    direction=direction, rung=int(target), lanes=int(lanes),
                    retired=int(nb_retired), active_replicas=keep,
                    reason=self.policy.last_reason)
        info("autoscale %s -> rung %d (lanes=%d, active replicas=%r): %s"
             % (direction, target, lanes, keep, self.policy.last_reason))
        if self.server.summaries is not None:
            self.server.summaries.event(
                self.server.scheduler.batch_count, "serve_autoscale", {
                    "direction": direction,
                    "rung": int(target),
                    "lanes": int(lanes),
                    "active_replicas": keep,
                    "reason": self.policy.last_reason,
                })

    def _retirement_plan(self, nb_retired):
        """Active indices keeping ``R - nb_retired`` replicas: the highest
        latest-disagreement scorers go first (a suspect replica is the
        first traded for capacity), non-finite scores first of all."""
        engine = self.server.engine
        scores = self.server.last_disagreement()

        def badness(index):
            score = scores[index] if index < len(scores) else 0.0
            if score != score:  # NaN: already retired, keep it retired first
                return (3, 0.0)
            if score in (float("inf"), float("-inf")):
                return (2, 0.0)
            return (1, float(score))

        order = sorted(range(engine.nb_replicas), key=badness, reverse=True)
        retired = set(order[:nb_retired])
        return [i for i in range(engine.nb_replicas) if i not in retired]

    # ------------------------------------------------------------------ #
    # lifecycle

    def start(self):
        """Tick every ``config.interval`` seconds on a daemon thread."""
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="serve-autoscaler"
            )
            thread = self._thread
        thread.start()

    def _run(self):
        while not self._stop.wait(self.config.interval):
            try:
                self.tick()
            except Exception as exc:  # a bad tick must not kill the pool
                info("autoscale tick failed: %s: %s"
                     % (type(exc).__name__, exc))

    def close(self):
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(5.0)
        for name in self._metric_names:
            self.registry.unregister(name)

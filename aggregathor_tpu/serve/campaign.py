"""Serving resilience campaign: replica faults x vote rules, measured.

The serving counterpart of ``chaos/campaign.py``: where the training
campaign proves a GAR absorbs Byzantine *gradients*, this harness proves the
replica vote absorbs Byzantine *replicas*.  Every cell of the
(vote GAR x replica fault) grid serves the SAME eval split through a real
:class:`serve.engine.InferenceEngine` whose replica set contains
``--nb-faulty`` corrupted members (``chaos/replica_faults.py`` modes: nan /
scale / zero / noise / stale), and reports

- ``accuracy``    served top-1 accuracy of the voted predictions;
- ``match_rate``  fraction of served predictions identical to the CLEAN
  single-replica baseline — the fault-masking verdict (``masked`` is
  ``match_rate >= --match-bar``; with identical clean replicas the median
  vote is *exactly* the clean model, so the bar defaults to 1.0);
- ``disagreement``  the engine's per-replica scores (the faulty replica
  must rank last / read null-for-inf).

The headline claim, as data (asserted by tests/test_serve.py and
``scripts/run_serve_smoke.sh``): ``median`` masks a NaN or scaled replica at
the clean bar while ``average`` degrades — the AggregaThor thesis carried
into the serving layer.

Since serve/ v2 every cell is served through the CONTINUOUS SCHEDULER
(``serve/continuous.py``), not by calling the engine directly: the eval
split is split into request-sized submissions fed concurrently to a
:class:`~.continuous.ContinuousBatcher` over the cell's engine, so the
verdicts cover the production dispatch path (batch formation, result
splitting, lane reuse) and each cell additionally reports the scheduler's
``batches`` count and the engine ``compile_count`` (the zero-recompile
contract: one executable per ladder bucket, at every cell).

The model is trained in-process (a short real training run through
``parallel.RobustEngine``) unless ``--ckpt-dir`` points at an existing
checkpoint; ``stale`` replicas snapshot the params early in that run (or the
oldest on-disk step with ``--ckpt-dir``).

Example (CPU, <60 s)::

  python -m aggregathor_tpu.serve.campaign \
      --experiment digits --train-steps 60 --replicas 3 \
      --gars median average --faults nan scale=100 \
      --output matrix.json --report report.md
"""

import argparse
import json
import sys

SCHEMA = "aggregathor.serve.replica-matrix.v2"

#: matrix keys every cell must carry (the smoke script asserts these)
CELL_KEYS = (
    "gar", "fault", "nb_replicas", "nb_faulty", "accuracy", "match_rate",
    "masked", "disagreement", "suspects", "batches", "compile_count",
)


def validate(doc):
    """Schema check for round-tripping consumers (the smoke script and
    tests/test_serve.py's round-trip test)."""
    if doc.get("schema") != SCHEMA:
        raise ValueError("not a %s document" % SCHEMA)
    for key in ("experiment", "nb_replicas", "nb_faulty", "steps_trained",
                "eval_rows", "match_bar", "clean_accuracy", "cells"):
        if key not in doc:
            raise ValueError("missing %r" % key)
    if not isinstance(doc["cells"], list) or not doc["cells"]:
        raise ValueError("cells must be a non-empty list")
    for cell in doc["cells"]:
        for key in CELL_KEYS:
            if key not in cell:
                raise ValueError("cell missing %r" % key)
        if not isinstance(cell["masked"], bool):
            raise ValueError("cell 'masked' must be a bool")
        if cell["batches"] < 1:
            raise ValueError("cell served zero scheduler batches")
    return doc


def load(path):
    with open(path) as fd:
        return validate(json.load(fd))


def build_parser():
    parser = argparse.ArgumentParser(
        prog="aggregathor-tpu serve-campaign",
        description="Replica-fault x vote-rule grid through the real inference engine",
    )
    parser.add_argument("--experiment", default="digits", help="experiment name (models registry)")
    parser.add_argument("--experiment-args", nargs="*", default=[], help="key:value experiment arguments")
    parser.add_argument("--gars", nargs="+", default=["median", "average"],
                        help="vote rules to sweep (gars registry; nb_workers = --replicas)")
    parser.add_argument("--gar-args", nargs="*", default=[], help="key:value arguments for every vote rule")
    parser.add_argument("--faults", nargs="*", default=["nan", "scale=100"],
                        help="replica fault scenarios MODE[=VALUE] "
                             "(chaos/replica_faults.py; 'clean' baseline is always prepended)")
    parser.add_argument("--replicas", type=int, default=3, help="replica count R")
    parser.add_argument("--nb-faulty", type=int, default=1,
                        help="corrupted replicas per fault cell (last indices)")
    parser.add_argument("--train-steps", type=int, default=60,
                        help="in-process training steps (ignored with --ckpt-dir)")
    parser.add_argument("--ckpt-dir", default=None,
                        help="serve an existing checkpoint instead of training in-process")
    parser.add_argument("--optimizer", default="sgd",
                        help="optimizer the --ckpt-dir snapshot was trained with (template rebuild)")
    parser.add_argument("--optimizer-args", nargs="*", default=[], help="key:value optimizer arguments")
    parser.add_argument("--learning-rate", type=float, default=0.05)
    parser.add_argument("--eval-rows", type=int, default=256,
                        help="eval rows served per cell (0 = the whole test split)")
    parser.add_argument("--max-batch", type=int, default=64, help="bucket ladder top")
    parser.add_argument("--request-rows", type=int, default=16,
                        help="rows per scheduler submission (the simulated client "
                             "request size the continuous batcher coalesces)")
    parser.add_argument("--lanes", type=int, default=2,
                        help="dispatch lanes the cell's scheduler runs")
    parser.add_argument("--match-bar", type=float, default=1.0,
                        help="masked verdict: match_rate >= this bar")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--output", default=None, metavar="JSON", help="replica matrix output path")
    parser.add_argument("--report", default=None, metavar="MD", help="markdown report output path")
    parser.add_argument("--platform", default=None, help="force a JAX platform (tpu/cpu)")
    return parser


def _parse_fault(item):
    """'nan' / 'scale=100' -> (name, mode, value) via the chaos spec parser."""
    from ..chaos.replica_faults import parse_poison

    _, mode, value = parse_poison("0:%s" % item)
    return item, mode, value


def train_in_process(experiment, nb_steps, lr, seed, stale_at=None):
    """Short real training run; returns (params, stale_params).

    ``stale_params`` is the parameter snapshot at step ``stale_at`` (default
    nb_steps // 4) — the under-trained replica the ``stale`` fault serves.
    """
    import jax

    from .. import gars
    from ..core import build_optimizer, build_schedule
    from ..parallel import RobustEngine, make_mesh

    n = 4
    gar = gars.instantiate("average", n, 0)
    tx = build_optimizer("sgd", build_schedule("fixed", ["initial-rate:%s" % lr]))
    engine = RobustEngine(make_mesh(nb_workers=1), gar, n)
    step = engine.build_step(experiment.loss, tx)
    state = engine.init_state(experiment.init(jax.random.PRNGKey(seed)), tx, seed=seed + 1)
    it = experiment.make_train_iterator(n, seed=seed + 2)
    if stale_at is None:
        stale_at = max(1, nb_steps // 4)
    stale_params = jax.device_get(state.params)
    for s in range(nb_steps):
        state, _ = step(state, engine.shard_batch(next(it)))
        if s + 1 == stale_at:
            stale_params = jax.device_get(state.params)
    return jax.device_get(state.params), stale_params


def serve_through_scheduler(engine, x, request_rows=16, lanes=2):
    """Serve ``x`` through a :class:`~.continuous.ContinuousBatcher` over
    ``engine`` — the production dispatch path — as a stream of
    ``request_rows``-sized submissions all in flight at once.

    Returns ``(predictions, disagreement, batches)``: predictions in row
    order, the rows-weighted mean per-replica disagreement over the
    dispatched batches (inf/NaN propagate — a faulty replica stays
    flagged), and the scheduler batch count (< number of submissions
    proves coalescing happened).
    """
    import numpy as np

    from .continuous import ContinuousBatcher

    request_rows = max(1, min(int(request_rows), engine.buckets[-1]))
    batcher = ContinuousBatcher(
        engine.predict, buckets=engine.buckets,
        queue_bound=max(len(x), 1), nb_lanes=lanes, max_lanes=lanes,
    )
    try:
        tickets = [
            batcher.submit(x[start:start + request_rows])
            for start in range(0, len(x), request_rows)
        ]
        results = [ticket.wait(120.0) for ticket in tickets]
    finally:
        batcher.close()
    predictions = np.concatenate([r["predictions"] for r in results])
    weights = np.asarray([len(r["predictions"]) for r in results], np.float64)
    scores = np.stack([np.asarray(r["disagreement"], np.float64) for r in results])
    disagreement = (scores * (weights / weights.sum())[:, None]).sum(axis=0)
    return predictions, disagreement, batcher.batch_count


def _eval_rows(experiment, limit):
    import numpy as np

    x = np.asarray(experiment.dataset.x_test, np.float32)
    # Engine predictions are argmax over the bare logits, which live in the
    # SHIFTED label space for experiments with a labels-offset (the zoo's
    # metrics compare against label - offset, models/zoo.py) — accuracy here
    # must compare in the same space.
    y = np.asarray(experiment.dataset.y_test) - getattr(experiment, "labels_offset", 0)
    if limit and limit > 0:
        x, y = x[:limit], y[:limit]
    return x, y


def run_campaign(args):
    import numpy as np

    from .. import gars, models
    from ..chaos.replica_faults import corrupt_params
    from ..utils import UserException, info
    from .engine import InferenceEngine, restore_params

    experiment = models.instantiate(args.experiment, args.experiment_args)
    if args.replicas < 1 or not 0 <= args.nb_faulty < args.replicas:
        raise UserException(
            "Need replicas >= 1 and 0 <= nb-faulty < replicas (got R=%d, faulty=%d)"
            % (args.replicas, args.nb_faulty)
        )
    if args.ckpt_dir:
        from ..core import build_optimizer, build_schedule

        tx = build_optimizer(
            args.optimizer, build_schedule("fixed", ["initial-rate:%s" % args.learning_rate]),
            args.optimizer_args,
        )
        params, at_step = restore_params(experiment, args.ckpt_dir, tx, seed=args.seed)
        steps_trained = at_step
        from ..obs import Checkpoints

        on_disk = Checkpoints(args.ckpt_dir).steps()
        stale_step = on_disk[0] if on_disk and on_disk[0] < at_step else None
        stale_params = (
            restore_params(experiment, args.ckpt_dir, tx, step=stale_step, seed=args.seed)[0]
            if stale_step is not None else params
        )
    else:
        params, stale_params = train_in_process(
            experiment, args.train_steps, args.learning_rate, args.seed
        )
        steps_trained = args.train_steps

    x_eval, y_eval = _eval_rows(experiment, args.eval_rows)
    info("Serve campaign: %s, %d eval rows, R=%d (%d faulty), trained %d step(s)"
         % (args.experiment, len(y_eval), args.replicas, args.nb_faulty, steps_trained))

    # The clean single-replica baseline every cell is judged against.
    baseline = InferenceEngine(experiment, [params], max_batch=args.max_batch)
    clean = baseline.predict(x_eval)
    clean_preds = clean["predictions"]
    clean_accuracy = float(np.mean(clean_preds == y_eval))

    scenarios = [("clean", None, None)]
    scenarios += [_parse_fault(item) for item in args.faults]

    cells = []
    for gar_name in args.gars:
        vote = gars.instantiate(
            gar_name, args.replicas, (args.replicas - 1) // 2, list(args.gar_args)
        )
        for fault_name, mode, value in scenarios:
            replicas = [params] * (args.replicas - (args.nb_faulty if mode else 0))
            for rank in range(args.nb_faulty if mode else 0):
                if mode == "stale":
                    replicas.append(stale_params)
                else:
                    replicas.append(corrupt_params(
                        params, mode, value, seed=args.seed + 17 * (rank + 1)
                    ))
            engine = InferenceEngine(
                experiment, replicas, gar=vote, max_batch=args.max_batch,
                seed=args.seed,
            )
            # v2: through the continuous scheduler — the production path
            preds, disagreement, batches = serve_through_scheduler(
                engine, x_eval, request_rows=args.request_rows,
                lanes=args.lanes,
            )
            suspects = [
                int(i) for i, v in enumerate(disagreement) if not np.isfinite(v)
            ]
            match_rate = float(np.mean(preds == clean_preds))
            cell = {
                "gar": gar_name,
                "fault": fault_name,
                "nb_replicas": args.replicas,
                "nb_faulty": int(args.nb_faulty if mode else 0),
                "accuracy": float(np.mean(preds == y_eval)),
                "match_rate": match_rate,
                "masked": bool(match_rate >= args.match_bar),
                "disagreement": [
                    float(v) if np.isfinite(v) else None for v in disagreement
                ],
                "suspects": suspects,
                "batches": int(batches),
                "compile_count": int(engine.compile_count),
                "nb_buckets": len(engine.buckets),
            }
            cells.append(cell)
            info("  cell %-12s x %-12s accuracy=%.3f match=%.3f masked=%s"
                 % (gar_name, fault_name, cell["accuracy"], match_rate, cell["masked"]))

    return {
        "schema": SCHEMA,
        "experiment": args.experiment,
        "nb_replicas": args.replicas,
        "nb_faulty": args.nb_faulty,
        "steps_trained": int(steps_trained),
        "eval_rows": int(len(y_eval)),
        "request_rows": int(args.request_rows),
        "lanes": int(args.lanes),
        "match_bar": args.match_bar,
        "clean_accuracy": clean_accuracy,
        "cells": cells,
    }


def write_report(matrix, path):
    gars_seen = sorted({c["gar"] for c in matrix["cells"]})
    faults = []
    for cell in matrix["cells"]:
        if cell["fault"] not in faults:
            faults.append(cell["fault"])
    by = {(c["gar"], c["fault"]): c for c in matrix["cells"]}
    lines = [
        "# Serving replica-fault campaign",
        "",
        "Experiment `%s` — R=%d replicas (%d faulty per fault cell), %d eval rows, "
        "clean single-replica accuracy **%.3f**.  A cell is **masked** when the "
        "voted predictions match the clean baseline at rate >= %.3f."
        % (matrix["experiment"], matrix["nb_replicas"], matrix["nb_faulty"],
           matrix["eval_rows"], matrix["clean_accuracy"], matrix["match_bar"]),
        "",
        "| vote \\ fault | " + " | ".join(faults) + " |",
        "|---|" + "---|" * len(faults),
    ]
    for gar_name in gars_seen:
        row = ["`%s`" % gar_name]
        for fault in faults:
            cell = by[(gar_name, fault)]
            row.append("%s acc %.3f / match %.3f"
                       % ("MASKED" if cell["masked"] else "degraded",
                          cell["accuracy"], cell["match_rate"]))
        lines.append("| " + " | ".join(row) + " |")
    lines += [
        "",
        "Per-replica disagreement flags the faulty members (null = non-finite "
        "= maximal): see `suspects` per cell in the JSON matrix.",
    ]
    with open(path, "w") as fd:
        fd.write("\n".join(lines) + "\n")


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.platform:
        import os

        os.environ["JAX_PLATFORMS"] = args.platform
        import jax

        jax.config.update("jax_platforms", args.platform)
    matrix = run_campaign(args)
    if args.output:
        with open(args.output, "w") as fd:
            json.dump(matrix, fd, indent=1)
    if args.report:
        write_report(matrix, args.report)
    if not args.output and not args.report:
        json.dump(matrix, sys.stdout, indent=1)
        sys.stdout.write("\n")
    return 0


def cli():
    from ..cli import console_entry

    return console_entry(main)


if __name__ == "__main__":
    sys.exit(cli())

"""The traffic plane: fleet admission + routing in front of N serving
processes.

PR 13 made one serving process fast and PR 15 made a fleet observable;
this module puts the fleet behind ONE door (docs/serving.md "The traffic
plane", the NET-SA framing — serving placement as an architecture
concern).  A :class:`FleetRouter` fronts N independent ``cli/serve.py``
processes (each a v2 stack following the same snapshot stream) and owns
four guarantees:

1. **Routing is a pure policy.**  :class:`RoutingPolicy` is clockless,
   socketless math over immutable :class:`BackendView` snapshots:
   least-in-flight among eligible backends, where eligibility = up, not
   draining, has queue capacity, and (when the client carries a step pin)
   known to serve ``weights_step >= pin``.  Health/pressure come from the
   PR-15 fleet scrape (an embedded :class:`~..obs.fleet.FleetCollector`
   polling each backend's ``/status`` + ``/metrics``) plus per-request
   outcomes — NEVER from one process's registry.
2. **Fleet-consistent weights_step.**  The router tracks each backend's
   served step from ``/predict`` responses and the scrape, and pins a
   client's session to backends at >= its last-seen step — so no client
   ever observes ``weights_step`` go backwards across replicas (the
   serve_load per-client monotone-sequence verdict, promoted fleet-wide).
   Because a backend's own step never regresses (the weight pipeline only
   swaps newer snapshots) and ``known_step`` is an observed lower bound,
   eligibility ``known_step >= pin`` implies the response cannot regress.
   During a swap window where NO backend has yet been seen at the pin,
   the router waits (bounded by ``step_wait_s``) for the fleet to catch
   up rather than serve an inconsistent read — consistency over
   availability, inside a bounded window.
3. **Shed is a fleet decision.**  A request is admitted while ANY
   healthy, non-draining backend has queue capacity; HTTP 429 fires only
   when the whole fleet is saturated (including the race where every
   capable backend sheds this very request).  A backend observed
   ``draining`` (``cli/serve.py`` SIGTERM) takes no NEW traffic while its
   in-flight requests finish.
4. **A mid-flight backend death drops nothing.**  A request whose
   forward dies on a transport error is re-dispatched onto a live backend
   EXACTLY once (``/predict`` is idempotent — pure inference), and the
   dead backend is latched out of the routable pool immediately, ahead of
   the scrape noticing.

Every router decision lands in the PR-15 causal journal
(``obs/events.py``): ``router_route`` (a client's backend assignment made
or changed — steady-state repeats of the same assignment stay off the
timeline, the journal's calm-rounds discipline), ``router_shed``,
``router_retry``, ``router_backend_down`` / ``router_backend_up``,
``router_drain`` and ``router_step_pin``.  The router exports its own
``/metrics`` (Prometheus by default, ``?format=json`` for the registry
snapshot) and ``/status`` from :class:`RouterServer`, so a
:class:`~..obs.fleet.FleetCollector` scrapes it like any other instance.

Run it: ``python -m aggregathor_tpu.cli.router --backend a=HOST:PORT
--backend b=HOST:PORT --port 9200``.
"""

import collections
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..obs import events as obs_events
from ..obs import metrics as obs_metrics
from ..obs.fleet import FleetCollector
from ..utils import UserException, info

#: the request header carrying the client/session identity the step pin
#: keys on; requests without it are routed (and counted) but not pinned
CLIENT_HEADER = "X-Client-Id"

#: the causal-plane header (docs/observability.md "The causal plane"):
#: a :func:`~..obs.events.format_cause` token naming the journal event
#: that caused this forward.  The router stamps its own latest event for
#: the dispatch (a caused ``router_route`` or a ``router_retry``) —
#: steady-state forwards pass an inbound client token through unchanged —
#: and backends echo the token into their ``/predict`` response, so a
#: postmortem can join the router's decision to the backend's answer.
CAUSAL_HEADER = "X-Causal-Id"

#: request bodies above this are refused outright (mirrors the front end)
MAX_BODY_BYTES = 64 * 1024 * 1024

BackendView = collections.namedtuple(
    "BackendView",
    ("name", "up", "draining", "in_flight", "queue_depth", "queue_bound",
     "at_ceiling", "known_step"),
)
BackendView.__doc__ = """One backend's immutable routing snapshot.

``in_flight`` is the ROUTER-side count (requests this router has
outstanding there — fresher than any scrape); ``queue_depth`` /
``queue_bound`` / ``at_ceiling`` come from the backend's ``/status``
pressure fields (``queue_bound`` None = unknown, treated as unbounded);
``known_step`` is the highest ``weights_step`` ever observed from this
backend (a lower bound on its live step, None until first observed)."""


class RoutingPolicy:
    """Pure routing/admission math over :class:`BackendView` rows.

    No clocks, no sockets, no mutable state — tests drive it on synthetic
    views (tests/test_router.py).  Subclass and override :meth:`route` to
    change the discipline; the router only calls these three methods.
    """

    @staticmethod
    def has_capacity(view):
        """Up, not draining, and its queue is not at the shed bound."""
        if not view.up or view.draining:
            return False
        return view.queue_bound is None or view.queue_depth < view.queue_bound

    def admit(self, views):
        """The FLEET admission verdict: admit while any backend has
        capacity; refusing here is the only path to a router 429."""
        return any(self.has_capacity(view) for view in views)

    def eligible(self, view, pin):
        """Routable for THIS client: capacity plus the step pin — a
        pinned client only lands on backends known to serve >= its pin
        (``known_step`` is a lower bound, so the response cannot
        regress)."""
        if not self.has_capacity(view):
            return False
        if pin is None:
            return True
        return view.known_step is not None and view.known_step >= pin

    def route(self, views, pin=None):
        """Least-in-flight among eligible backends (name-ordered
        tie-break, so the choice is deterministic for tests); None when
        nobody is eligible — the caller decides between shedding (no
        capacity anywhere) and waiting out a swap window (capacity
        exists, the pin starves)."""
        candidates = [v for v in views if self.eligible(v, pin)]
        if not candidates:
            return None
        return min(candidates, key=lambda v: (v.in_flight, v.name)).name


class _Backend:
    """Router-side runtime state for one backend (lock-protected)."""

    __slots__ = ("name", "url", "in_flight", "known_step", "draining",
                 "alive", "status", "dispatched", "failures",
                 "down_event", "drain_event")

    def __init__(self, name, url):
        self.name = name
        self.url = url
        self.in_flight = 0
        self.known_step = None
        self.draining = False
        self.alive = None     # None = never scraped, else bool
        self.status = {}      # last /status body seen by the scrape
        self.dispatched = 0
        self.failures = 0
        self.down_event = None   # last router_backend_down record (cause)
        self.drain_event = None  # last router_drain record (cause)


class _Session:
    """One client's pin + assignment (the step-consistency state)."""

    __slots__ = ("pin", "backend", "pin_event")

    def __init__(self):
        self.pin = None
        self.backend = None
        self.pin_event = None    # last router_step_pin record (cause)


class FleetRouter:
    """The admission/routing runtime over N serving backends.

    Args:
      backends: ``{name: base_url}`` (``host:port`` normalized to http).
      policy: a :class:`RoutingPolicy` (default constructed).
      registry: metrics registry (default the process-wide one — the
        router is its own process).
      poll_interval: seconds between fleet scrapes (:meth:`start`).
      down_after: consecutive scrape misses before the collector reads a
        backend down (per-request failures latch it out IMMEDIATELY).
      timeout: per-scrape fetch timeout.
      request_timeout_s: forward timeout for ``/predict`` (must exceed
        the backends' own batch wait).
      step_wait_s: how long a pinned request may wait for SOME backend to
        reach its pin during a swap window before giving up (503).
      instance_name: this router's fleet-instance name — the ``instance``
        field of the :data:`CAUSAL_HEADER` tokens it stamps (must match
        the name its journal is merged under in ``/fleet/journal``).
      fetch / post / clock / sleep: injectable transports and time — the
        synthetic-clock tests drive every path without sockets.  ``post``
        is ``post(url, body, timeout, headers) -> (code, body_bytes)``.
    """

    def __init__(self, backends, policy=None, registry=None,
                 poll_interval=0.5, down_after=3, timeout=2.0,
                 request_timeout_s=60.0, step_wait_s=5.0,
                 instance_name="router",
                 fetch=None, post=None, clock=None, sleep=None):
        if not backends:
            raise UserException("FleetRouter wants at least one backend")
        if float(step_wait_s) < 0:
            raise UserException("step_wait_s must be >= 0")
        self.instance_name = str(instance_name)
        self.policy = policy if policy is not None else RoutingPolicy()
        self.registry = registry if registry is not None else obs_metrics.REGISTRY
        self.poll_interval = float(poll_interval)
        self.request_timeout_s = float(request_timeout_s)
        self.step_wait_s = float(step_wait_s)
        self.clock = clock if clock is not None else time.monotonic
        self._sleep = sleep if sleep is not None else time.sleep
        self._post = post if post is not None else _default_post
        self._lock = threading.Lock()
        self._backends = {}
        for name, url in backends.items():
            if "://" not in url:
                url = "http://" + url
            self._backends[str(name)] = _Backend(str(name), url.rstrip("/"))
        self._sessions = {}
        self._stop = threading.Event()
        self._thread = None
        # health/pressure through the PR-15 fleet scrape — the one-scrape
        # federation plane, never a single process's registry
        self.collector = FleetCollector(
            backends, down_after=down_after, timeout=timeout, fetch=fetch,
            clock=clock,
        )
        self._metric_names = [
            "router_requests_total", "router_forwards_total",
            "router_retries_total", "router_sheds_total",
            "router_backend_up", "router_backend_inflight",
            "router_sessions", "router_step_pin_waits_total",
            "router_request_latency_seconds",
        ]
        self._m_requests = self.registry.counter(
            "router_requests_total", "Requests answered by the router",
            labelnames=("code",),
        )
        self._m_forwards = self.registry.counter(
            "router_forwards_total", "Forwards dispatched per backend",
            labelnames=("backend",),
        )
        self._m_retries = self.registry.counter(
            "router_retries_total",
            "Requests re-dispatched after their backend died mid-flight",
        )
        self._m_sheds = self.registry.counter(
            "router_sheds_total", "Fleet-saturated admission refusals (429)"
        )
        self._m_up = self.registry.gauge(
            "router_backend_up", "1 while the backend is routable",
            labelnames=("backend",),
        )
        self._m_inflight = self.registry.gauge(
            "router_backend_inflight",
            "Router-side in-flight forwards per backend",
            labelnames=("backend",),
        )
        self.registry.gauge(
            "router_sessions", "Client sessions with a step pin"
        ).set_function(lambda: len(self._sessions))
        self._m_pin_waits = self.registry.counter(
            "router_step_pin_waits_total",
            "Requests that waited out a swap window for a pinned backend",
        )
        self.latency = self.registry.histogram(
            "router_request_latency_seconds", "End-to-end routed latency"
        )
        for name in self._backends:
            self._m_up.labels(backend=name).set(0.0)
            self._m_inflight.labels(backend=name).set(0.0)

    # ------------------------------------------------------------------ #
    # fleet state: scrape sync + per-request outcomes

    def poll_once(self):
        """One scrape cycle + state sync (the poll thread's body; tests
        call it directly under synthetic fetch/clock)."""
        self.collector.poll_once()
        status = self.collector.status_payload()["instances"]
        for name, entry in status.items():
            backend = self._backends.get(name)
            if backend is None:
                continue
            if entry["up"]:
                body = entry["status"] if isinstance(entry["status"], dict) else {}
                self._mark_up(backend, body)
            elif entry["stale"]:
                # ever seen, now missing scrapes: an explicit down
                self._mark_down(backend, "scrape_misses")

    def _mark_up(self, backend, status_body):
        with self._lock:
            recovered = backend.alive is False
            backend.alive = True
            backend.status = status_body
            step = status_body.get("weights_step")
            if isinstance(step, int) and (backend.known_step is None
                                          or step > backend.known_step):
                backend.known_step = step
            draining = bool(status_body.get("draining"))
            began_drain = draining and not backend.draining
            in_flight = backend.in_flight
            backend.draining = draining
        self._m_up.labels(backend=backend.name).set(0.0 if draining else 1.0)
        if recovered:
            obs_events.emit("router_backend_up", backend=backend.name)
        if began_drain:
            record = obs_events.emit("router_drain", backend=backend.name,
                                     in_flight=in_flight)
            with self._lock:
                backend.drain_event = record

    def _mark_down(self, backend, reason):
        """Latch a backend out; returns the ``router_backend_down`` record
        (None when already down or journaling is off) — the cause the
        re-route / retry it triggers will cite."""
        with self._lock:
            was_alive = backend.alive
            backend.alive = False
            backend.failures += 1
        self._m_up.labels(backend=backend.name).set(0.0)
        if was_alive or was_alive is None:
            record = obs_events.emit("router_backend_down",
                                     backend=backend.name, reason=reason)
            with self._lock:
                backend.down_event = record
            return record
        return None

    # ------------------------------------------------------------------ #
    # views + sessions

    def views(self, exclude=()):
        """Immutable :class:`BackendView` rows for the policy."""
        with self._lock:
            rows = []
            for backend in self._backends.values():
                if backend.name in exclude:
                    continue
                status = backend.status
                bound = status.get("queue_bound")
                rows.append(BackendView(
                    name=backend.name,
                    up=bool(backend.alive),
                    draining=backend.draining,
                    in_flight=backend.in_flight,
                    queue_depth=int(status.get("queue_depth") or 0),
                    queue_bound=int(bound) if isinstance(bound, int) else None,
                    at_ceiling=bool(status.get("at_ceiling")),
                    known_step=backend.known_step,
                ))
            return rows

    def _session(self, client_id):
        if client_id is None:
            return None
        with self._lock:
            session = self._sessions.get(client_id)
            if session is None:
                session = self._sessions[client_id] = _Session()
            return session

    def _note_assignment(self, client_id, session, choice, pin,
                         inbound_cause=None):
        """Journal a client's backend assignment when it changes FOR A
        CAUSE (first contact, the previous backend down/draining, or the
        step pin excluding it).  Steady-state least-in-flight moves
        between equally-healthy backends are the calm case and stay off
        the timeline — the PR-15 journal discipline; a 3-backend fleet
        under closed-loop load would otherwise write hundreds of route
        lines per second that replay nothing.

        Returns the emitted ``router_route`` record (None for steady-state
        moves or with journaling off) — the latest causal event for this
        dispatch, stamped onto the forward as :data:`CAUSAL_HEADER`.  The
        route cites ITS cause: the down/drain event that evicted the
        previous backend, or the step-pin advance that excluded it
        (the inbound client token for first contact)."""
        if session is None:
            return None
        cause = None
        with self._lock:
            previous = session.backend
            if previous == choice:
                return None
            session.backend = choice
            if previous is None:
                reason = "initial"
                cause = inbound_cause
            else:
                old = self._backends.get(previous)
                if old is None or not old.alive:
                    reason = "backend_down"
                    if old is not None and old.down_event is not None:
                        cause = obs_events.cause_of(old.down_event)
                elif old.draining:
                    reason = "drain"
                    if old.drain_event is not None:
                        cause = obs_events.cause_of(old.drain_event)
                elif pin is not None and (old.known_step is None
                                          or old.known_step < pin):
                    reason = "step_pin"
                    if session.pin_event is not None:
                        cause = obs_events.cause_of(session.pin_event)
                else:
                    reason = "rebalance"
        if reason != "rebalance":
            return obs_events.emit("router_route", client=client_id,
                                   backend=choice, previous=previous,
                                   reason=reason, step_pin=pin, cause=cause)
        return None

    def _observe_step(self, name, client_id, session, step):
        """A 200 response reported its served ``weights_step``: raise the
        backend's known lower bound and (for pinned clients) advance the
        session pin — the advancement is the journaled decision."""
        if not isinstance(step, int):
            return
        advanced = None
        with self._lock:
            backend = self._backends.get(name)
            if backend is not None and (backend.known_step is None
                                        or step > backend.known_step):
                backend.known_step = step
            if session is not None and (session.pin is None
                                        or step > session.pin):
                advanced = (session.pin, step)
                session.pin = step
        if advanced is not None:
            record = obs_events.emit("router_step_pin", client=client_id,
                                     backend=name, previous=advanced[0],
                                     pin=advanced[1])
            with self._lock:
                if session is not None:
                    session.pin_event = record

    # ------------------------------------------------------------------ #
    # the request path

    def handle_predict(self, body, client_id=None, causal_id=None):
        """Route one ``/predict`` body; returns ``(code, payload_dict)``.

        The loop either returns, excludes a backend (shed this request /
        died mid-flight), or waits out a swap window bounded by
        ``step_wait_s`` — so it terminates.  A transport death is retried
        EXACTLY once; ``/predict`` is idempotent (pure inference), so the
        re-dispatch cannot double-apply anything.

        ``causal_id`` is the request's inbound :data:`CAUSAL_HEADER` token
        (None when absent).  The forward carries the router's latest
        journal event for this dispatch as the header — a caused
        ``router_route`` or a ``router_retry`` — falling back to the
        inbound token unchanged; a garbled inbound token is dropped, never
        a request failure (observability must not shed traffic).
        """
        started = self.clock()
        session = self._session(client_id)
        deadline = started + self.step_wait_s
        excluded = set()
        retried = False
        waited = False
        inbound_cause = None
        forward_token = None
        if causal_id is not None:
            try:
                inbound_cause = obs_events.parse_cause(causal_id)
                forward_token = causal_id
            except ValueError:
                pass
        while True:
            views = self.views(exclude=excluded)
            if not any(v.up and not v.draining for v in views):
                return self._answer(503, {
                    "error": "no live backend",
                    "detail": "every backend is down or draining",
                })
            if not self.policy.admit(views):
                self._m_sheds.inc()
                obs_events.emit("router_shed", client=client_id,
                                excluded=sorted(excluded),
                                detail="fleet saturated")
                return self._answer(429, {"error": "shed",
                                          "detail": "fleet saturated"})
            pin = session.pin if session is not None else None
            choice = self.policy.route(views, pin)
            if choice is None:
                # capacity exists but nobody is known at >= pin yet: a
                # swap window — wait for the fleet to catch up instead of
                # serving a step that could read backwards
                if not waited:
                    waited = True
                    self._m_pin_waits.inc()
                if self.clock() >= deadline:
                    return self._answer(503, {
                        "error": "no backend at pinned step",
                        "detail": "fleet did not reach weights_step >= %r "
                                  "within %.1fs" % (pin, self.step_wait_s),
                    })
                self._sleep(0.02)
                self.poll_once()
                continue
            backend = self._backends[choice]
            route_event = self._note_assignment(client_id, session, choice,
                                               pin, inbound_cause)
            if route_event is not None:
                forward_token = obs_events.format_cause(
                    obs_events.cause_of(route_event, self.instance_name))
            headers = ({CAUSAL_HEADER: forward_token}
                       if forward_token is not None else {})
            with self._lock:
                backend.in_flight += 1
                backend.dispatched += 1
            self._m_inflight.labels(backend=choice).set(backend.in_flight)
            self._m_forwards.labels(backend=choice).inc()
            try:
                code, payload = self._post(
                    backend.url + "/predict", body, self.request_timeout_s,
                    headers,
                )
            except (OSError, ValueError) as exc:
                # transport death (URLError/ConnectionError/timeout are
                # all OSError; ValueError covers a torn chunked read):
                # latch the backend out NOW — ahead of the scrape — and
                # re-dispatch exactly once
                down_event = self._mark_down(
                    backend, "request_failure: %s" % type(exc).__name__)
                excluded.add(choice)
                if retried:
                    return self._answer(502, {
                        "error": "backend lost",
                        "detail": "two backends died mid-flight",
                    })
                retried = True
                self._m_retries.inc()
                # the second attempt cites the first attempt's failure
                retry_event = obs_events.emit(
                    "router_retry", client=client_id, backend=choice,
                    reason=type(exc).__name__,
                    cause=(obs_events.cause_of(down_event)
                           if down_event is not None else inbound_cause))
                if retry_event is not None:
                    forward_token = obs_events.format_cause(
                        obs_events.cause_of(retry_event, self.instance_name))
                continue
            finally:
                with self._lock:
                    backend.in_flight -= 1
                self._m_inflight.labels(backend=choice).set(backend.in_flight)
            if isinstance(payload, (bytes, str)):
                try:
                    payload = json.loads(payload or b"{}")
                except ValueError:
                    payload = {"error": "unparseable backend response"}
            if code == 429:
                # the backend shed in the race window since the scrape:
                # per-request outcome feeds back into the fleet decision —
                # try the rest of the fleet before answering 429
                excluded.add(choice)
                continue
            if code == 200:
                self._observe_step(choice, client_id, session,
                                   payload.get("weights_step"))
                self.latency.record(max(0.0, self.clock() - started))
            return self._answer(code, payload, routed=choice)

    def _answer(self, code, payload, routed=None):
        self._m_requests.labels(code=str(code)).inc()
        if routed is not None and isinstance(payload, dict):
            payload = dict(payload, backend=routed)
        return code, payload

    # ------------------------------------------------------------------ #
    # introspection

    def status_payload(self):
        """The router's own ``/status`` body — scraped by an outer
        FleetCollector like any other instance."""
        with self._lock:
            backends = {}
            for backend in self._backends.values():
                backends[backend.name] = {
                    "url": backend.url,
                    "up": bool(backend.alive),
                    "draining": backend.draining,
                    "in_flight": backend.in_flight,
                    "dispatched": backend.dispatched,
                    "failures": backend.failures,
                    "known_step": backend.known_step,
                    "queue_depth": backend.status.get("queue_depth"),
                    "queue_bound": backend.status.get("queue_bound"),
                    "at_ceiling": backend.status.get("at_ceiling"),
                }
            sessions = len(self._sessions)
        return {
            "role": "router",
            "backends": backends,
            "sessions": sessions,
            "polls": self.collector.polls_total,
        }

    # ------------------------------------------------------------------ #
    # lifecycle

    def start(self):
        """One immediate scrape (so the first request sees the fleet),
        then poll on a daemon thread every ``poll_interval`` seconds."""
        if self._thread is not None:
            return
        self.poll_once()

        def run():
            while not self._stop.wait(self.poll_interval):
                self.poll_once()

        self._thread = threading.Thread(
            target=run, daemon=True, name="fleet-router-poll"
        )
        self._thread.start()

    def close(self):
        """Stop the poll loop and release this router's instruments."""
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(5.0)
        for name in self._metric_names:
            self.registry.unregister(name)


def _default_post(url, body, timeout, headers=None):
    """(code, body_bytes) for a JSON POST; transport errors raise (the
    router's retry-once path), HTTP error codes return normally.
    ``headers`` are extra request headers (the causal-plane stamp)."""
    merged = {"Content-Type": "application/json"}
    if headers:
        merged.update(headers)
    request = urllib.request.Request(url, data=body, headers=merged)
    try:
        with urllib.request.urlopen(request, timeout=timeout) as response:
            return response.status, response.read()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read()


# --------------------------------------------------------------------- #
# the one-port HTTP face


class _RouterHandler(BaseHTTPRequestHandler):
    server_version = "aggregathor-router/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # scrapes must not spam stderr
        pass

    def _reply(self, code, body, content_type="application/json"):
        body = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        path = urllib.parse.urlsplit(self.path).path
        if path != "/predict":
            self._reply(404, json.dumps({"error": "unknown path %r" % path}))
            return
        try:
            length = int(self.headers.get("Content-Length", "0") or 0)
        except ValueError:
            self._reply(400, json.dumps({"error": "bad Content-Length"}))
            return
        if length < 0 or length > MAX_BODY_BYTES:
            self._reply(400, json.dumps(
                {"error": "unacceptable Content-Length %d" % length}))
            return
        body = self.rfile.read(length) if length else b""
        client_id = self.headers.get(CLIENT_HEADER)
        causal_id = self.headers.get(CAUSAL_HEADER)
        try:
            code, payload = self.server.router.handle_predict(
                body, client_id=client_id, causal_id=causal_id
            )
        except Exception as exc:  # a request must never kill the router
            code, payload = 500, {"error": "%s: %s"
                                  % (type(exc).__name__, exc)}
        self._reply(code, json.dumps(payload))

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        router = self.server.router
        if parsed.path == "/metrics":
            fmt = urllib.parse.parse_qs(parsed.query).get("format", [None])[0]
            if fmt == "json":
                self._reply(200, json.dumps(router.registry.snapshot()))
            elif fmt in (None, "prometheus"):
                self._reply(200, router.registry.render_prometheus(),
                            obs_metrics.PROMETHEUS_CONTENT_TYPE)
            else:
                self._reply(400, json.dumps(
                    {"error": "unknown metrics format %r" % fmt}))
        elif parsed.path == "/status":
            self._reply(200, json.dumps(router.status_payload()))
        elif parsed.path == "/healthz":
            self._reply(200, json.dumps({"status": "ok", "role": "router"}))
        else:
            self._reply(404, json.dumps(
                {"error": "unknown path %r" % parsed.path}))


class RouterServer(ThreadingHTTPServer):
    """The router's HTTP face (``serve_background`` / ``shutdown_all``,
    the LiveExporter lifecycle): ``POST /predict`` routed through the
    fleet, ``GET /metrics`` + ``/status`` + ``/healthz`` for the scrape
    plane."""

    daemon_threads = True

    def __init__(self, router, host="127.0.0.1", port=0):
        super().__init__((host, int(port)), _RouterHandler)
        self.router = router
        self._serve_thread = None

    def serve_background(self):
        self._serve_thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="fleet-router"
        )
        self._serve_thread.start()
        host, port = self.server_address[:2]
        info("Fleet router on http://%s:%d (/predict, /metrics, /status)"
             % (host, port))
        return host, port

    def shutdown_all(self):
        self.shutdown()
        self.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(5.0)
            self._serve_thread = None

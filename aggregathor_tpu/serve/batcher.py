"""Deadline micro-batching with bounded backpressure and load-shedding.

The serving input pipeline: request rows accumulate in a queue and are
dispatched as one bucket-shaped batch when EITHER (a) the oldest request has
waited ``max_latency_s`` (the deadline — the tail-latency contract, the
OptiReduce framing: bound the tail rather than wait for the last straggler)
OR (b) ``max_batch`` rows are ready (the occupancy cap — a full bucket gains
nothing by waiting).  The deadline-vs-straggler tradeoff of AllReduce maps
onto serving verbatim: a late request is the straggler, and the deadline
bounds how long everyone else's latency is hostage to it.

Backpressure is explicit: once the queued row count would pass
``queue_bound``, ``submit`` fails IMMEDIATELY with :class:`LoadShed` (the
429 path) instead of growing the queue — under overload, shedding keeps the
served requests' latency bounded instead of letting every request time out
(load-shedding is the serving counterpart of the lossy link's
drop-don't-block transport).  The bound caps *waiting* work only: a request
arriving to an empty queue is always admitted, so any request of up to
``max_batch`` rows is servable by an idle server regardless of the bound.

The batcher is engine-agnostic: ``runner`` is any callable taking a
``(k, *sample)`` row block and returning a dict of leading-``k`` arrays
(plus optional scalar extras, broadcast to every request in the batch).
"""

import threading
import time

import numpy as np

from ..obs import trace


class LoadShed(Exception):
    """Raised by ``submit`` when the queue is over ``queue_bound`` rows —
    map to HTTP 429 (``serve/server.py``)."""


class _Pending:
    __slots__ = ("rows", "event", "result", "error", "enqueued_at")

    def __init__(self, rows, now):
        self.rows = rows
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.enqueued_at = now


class Ticket:
    """Handle for one submitted request: ``wait()`` blocks for the batch
    carrying it and returns the per-request result dict.  A timed-out wait
    CANCELS the request: if it is still queued it is removed (the engine
    never runs dead work for a caller that already gave up — under
    saturation that capacity goes to still-live requests); if its batch is
    already in flight, the result is simply dropped."""

    def __init__(self, batcher, pending):
        self._batcher = batcher
        self._pending = pending

    def wait(self, timeout=None):
        if not self._pending.event.wait(timeout):
            self._batcher._cancel(self._pending)
            raise TimeoutError("inference batch did not complete in time")
        if self._pending.error is not None:
            raise self._pending.error
        return self._pending.result


class MicroBatcher:
    """Queue + dispatcher thread in front of an inference runner.

    Args:
      runner: ``(rows) -> dict`` — typically ``InferenceEngine.predict``.
        Leading-axis-``k`` values are split per request; other values
        (disagreement vectors, bucket scalars) are shared by every request
        in the batch.
      max_latency_s: dispatch deadline measured from the OLDEST queued
        request's arrival.
      max_batch: row cap per dispatched batch (the ladder top).
      queue_bound: queued-row limit beyond which ``submit`` sheds.
      clock: injectable monotonic clock (tests).
    """

    #: result keys never split per request even when their leading dimension
    #: happens to equal the batch's row count (e.g. R replicas == k rows)
    SHARED_KEYS = ("disagreement", "bucket")

    def __init__(self, runner, max_latency_s=0.010, max_batch=64,
                 queue_bound=256, clock=time.monotonic, on_batch=None,
                 shared_keys=SHARED_KEYS):
        if max_batch < 1 or queue_bound < 1 or max_latency_s < 0:
            raise ValueError(
                "MicroBatcher wants max_batch>=1, queue_bound>=1, max_latency_s>=0"
            )
        self.runner = runner
        self.max_latency_s = float(max_latency_s)
        self.max_batch = int(max_batch)
        self.queue_bound = int(queue_bound)
        self.clock = clock
        self.on_batch = on_batch
        self.shared_keys = frozenset(shared_keys)
        self._queue = []
        self._queued_rows = 0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self.shed_count = 0
        self.batch_count = 0
        self.served_rows = 0
        #: occupancy of the last dispatched batch: (rows, cap)
        self.last_occupancy = (0, self.max_batch)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="micro-batcher"
        )
        self._thread.start()

    # ------------------------------------------------------------------ #
    # producer side

    def submit(self, rows):
        """Enqueue ``rows`` ((k, *sample) array, k >= 1); returns a
        :class:`Ticket`.  Sheds with :class:`LoadShed` when the queue is
        over bound, full requests only (a request never splits across
        batches: ``k`` must fit ``max_batch``)."""
        rows = np.asarray(rows)
        k = rows.shape[0]
        if k < 1:
            raise ValueError("Empty request")
        if k > self.max_batch:
            raise ValueError(
                "Request of %d rows exceeds max_batch=%d; split it client-side"
                % (k, self.max_batch)
            )
        with self._lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            # The bound caps WAITING work: a request arriving to an empty
            # queue is always admitted (it dispatches next and delays
            # nobody) — otherwise a request larger than the bound could
            # never be served, even by an idle server.
            if self._queued_rows and self._queued_rows + k > self.queue_bound:
                self.shed_count += 1
                trace.instant("serve.shed", cat="serve", rows=k,
                              queued_rows=self._queued_rows)
                raise LoadShed(
                    "queue at %d/%d rows; request of %d rows shed"
                    % (self._queued_rows, self.queue_bound, k)
                )
            pending = _Pending(rows, self.clock())
            self._queue.append(pending)
            self._queued_rows += k
            self._wake.notify()
        trace.instant("serve.enqueue", cat="serve", rows=k)
        return Ticket(self, pending)

    def _cancel(self, pending):
        """Drop a still-queued request (timed-out Ticket.wait); no-op when
        its batch was already taken by the dispatcher."""
        with self._lock:
            if pending in self._queue:
                self._queue.remove(pending)
                self._queued_rows -= pending.rows.shape[0]
        pending.error = TimeoutError("request cancelled after wait timeout")
        pending.event.set()

    @property
    def queue_depth(self):
        """Queued rows awaiting dispatch (the backpressure signal)."""
        with self._lock:
            return self._queued_rows

    def close(self, timeout=5.0):
        """Stop the dispatcher; queued requests are failed, not served."""
        with self._lock:
            self._closed = True
            leftovers, self._queue = self._queue, []
            self._queued_rows = 0
            self._wake.notify()
        for pending in leftovers:
            pending.error = RuntimeError("MicroBatcher closed")
            pending.event.set()
        self._thread.join(timeout)

    # ------------------------------------------------------------------ #
    # dispatcher side

    def _take_batch(self):
        """Block until a batch is due (deadline or cap), then pop it.
        Returns None when closed."""
        with self._lock:
            while True:
                if self._closed:
                    return None
                if self._queue:
                    oldest = self._queue[0].enqueued_at
                    due_at = oldest + self.max_latency_s
                    rows_ready = sum(p.rows.shape[0] for p in self._queue)
                    now = self.clock()
                    if rows_ready >= self.max_batch or now >= due_at:
                        break
                    self._wake.wait(due_at - now)
                else:
                    self._wake.wait()
            batch, used = [], 0
            while self._queue and used + self._queue[0].rows.shape[0] <= self.max_batch:
                pending = self._queue.pop(0)
                used += pending.rows.shape[0]
                batch.append(pending)
            self._queued_rows -= used
            return batch

    def _loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            rows = np.concatenate([p.rows for p in batch]) if len(batch) > 1 else batch[0].rows
            started = self.clock()
            try:
                with trace.span("serve.batch", cat="serve",
                                rows=int(rows.shape[0]), requests=len(batch)):
                    out = self.runner(rows)
            except Exception as exc:  # surfaced per ticket, batcher survives
                for pending in batch:
                    pending.error = exc
                    pending.event.set()
                continue
            k = rows.shape[0]
            offset = 0
            for pending in batch:
                span = pending.rows.shape[0]
                result = {}
                for name, value in out.items():
                    if (name not in self.shared_keys
                            and isinstance(value, np.ndarray)
                            and value.ndim >= 1 and value.shape[0] == k):
                        result[name] = value[offset:offset + span]
                    else:
                        result[name] = value  # batch-shared extras
                pending.result = result
                offset += span
                pending.event.set()
            self.batch_count += 1
            self.served_rows += k
            self.last_occupancy = (k, self.max_batch)
            if self.on_batch is not None:
                self.on_batch(rows=k, requests=len(batch),
                              latency_s=self.clock() - started, output=out)

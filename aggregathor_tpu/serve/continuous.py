"""Continuous (in-flight) batching on the bucket ladder.

PR 3's ``MicroBatcher`` held every request until a DEADLINE (the oldest
request's ``max_latency``) or a full batch — under sustained traffic that
is the wrong discipline twice over: a lone request on an idle engine waits
the whole deadline for company that never comes, and while one batch is in
flight the dispatcher sits behind the same deadline instead of forming the
next batch the moment capacity frees.  Continuous batching inverts it:

- a request is dispatched **as soon as a bucket slot (lane) is free** —
  an idle server never waits;
- while every lane is busy, arrivals accumulate and **join the next
  dispatch the moment a lane frees** — batching emerges from in-flight
  time instead of from an imposed wait, so occupancy rises exactly when
  load does (the serving twin of bounded-wait aggregation: capacity is
  never hostage to a timer);
- formation is strictly FIFO off the queue head, so an old request can
  never be bypassed by younger ones (starvation-freedom — asserted by
  tests/test_serve_sched.py).

The scheduling decision itself lives in :class:`ContinuousPolicy`, a PURE
policy object in the ``parallel/deadline.py`` style: it consumes a queue
snapshot and a clock reading and returns a plan — no threads, no wall
clock, testable against synthetic time.  :class:`ContinuousBatcher` is the
runtime around it: a pool of dispatch **lanes** (one in-flight bucket
each; ``set_lanes`` resizes the pool live — the autoscaler's capacity
lever, ``serve/autoscale.py``) driving one shared compiled engine, so any
lane count reuses the SAME bucket-ladder executables and the
zero-recompile contract (``compile_count == len(buckets)``) holds at every
scale.

Backpressure keeps PR 3's explicit contract: over ``queue_bound`` queued
rows, ``submit`` raises :class:`LoadShed` (the 429 path) instead of
growing the queue; the bound caps WAITING work only (an empty queue always
admits).  A timed-out ``Ticket.wait`` (the 504 path) CANCELS its
still-queued rows so lanes never run dead work under saturation.

Unlike the MicroBatcher's baselined single-writer telemetry, every shared
attribute here is written under the one scheduler lock — the graftcheck
concurrency lint (CC001, docs/analysis.md) passes with ZERO baseline
entries for this module.
"""

import threading
import time

import numpy as np

from ..obs import trace
from ..utils import UserException, info
from .engine import choose_bucket


class LoadShed(Exception):
    """Raised by ``submit`` when the queue is over ``queue_bound`` rows —
    map to HTTP 429 (``serve/frontend.py``)."""


class _Pending:
    """One submitted request travelling through the scheduler."""

    __slots__ = ("rows", "event", "result", "error", "enqueued_at",
                 "_lock", "_callbacks", "_done")

    def __init__(self, rows, now):
        self.rows = rows
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.enqueued_at = now
        self._lock = threading.Lock()
        self._callbacks = []
        self._done = False

    def finish(self, result=None, error=None):
        """Complete exactly once; late completions (a cancelled request's
        batch landing anyway) are dropped.  Returns whether this call won."""
        with self._lock:
            if self._done:
                return False
            self._done = True
            self.result = result
            self.error = error
            callbacks, self._callbacks = self._callbacks, []
        self.event.set()
        for callback in callbacks:
            try:
                callback(self)
            except Exception as exc:  # a bad callback must not kill a lane
                info("serve ticket callback failed: %s: %s"
                     % (type(exc).__name__, exc))
        return True

    def add_done_callback(self, callback):
        """Run ``callback(pending)`` on completion — immediately when
        already done, else from the completing thread (the asyncio front
        end bridges this to its event loop)."""
        with self._lock:
            if not self._done:
                self._callbacks.append(callback)
                return
        callback(self)


class Ticket:
    """Handle for one submitted request.

    ``wait()`` blocks for the batch carrying it (threaded callers);
    ``add_done_callback`` delivers the completion without a blocked thread
    (the asyncio front end's path — one event loop awaits thousands of
    tickets without a thread each).  A timed-out ``wait`` CANCELS the
    request: still-queued rows are removed (lanes never run dead work for
    a caller that already got its 504); an in-flight batch's result is
    simply dropped.
    """

    def __init__(self, batcher, pending):
        self._batcher = batcher
        self._pending = pending

    def wait(self, timeout=None):
        if not self._pending.event.wait(timeout):
            self.cancel()
            raise TimeoutError("inference batch did not complete in time")
        if self._pending.error is not None:
            raise self._pending.error
        return self._pending.result

    def cancel(self):
        """Remove the request from the queue if still waiting; no-op once
        its batch is in flight.  Returns whether it was still queued."""
        return self._batcher._cancel(self._pending)

    def add_done_callback(self, callback):
        self._pending.add_done_callback(callback)

    @property
    def done(self):
        return self._pending.event.is_set()


class ContinuousPolicy:
    """Pure batch-formation policy: queue snapshot + clock in, plan out.

    The policy is deterministic in its inputs (no wall clock, no threads —
    the ``parallel/deadline.py`` discipline), so the scheduling math is
    pinned against synthetic traces by tests/test_serve_sched.py:

    - ``admit``: the load-shedding decision — over ``queue_bound`` queued
      rows a new request sheds; an empty queue ALWAYS admits (the bound
      caps waiting work, so any request up to the ladder top is servable
      by an idle server regardless of the bound).
    - ``plan``: given the pending queue (oldest first) and ``now``,
      either ``("dispatch", (nb_requests, bucket))`` — take the FIFO
      prefix that fits the ladder top, padded up to the smallest covering
      bucket — or ``("wait", due_at)`` while a sub-top batch may still
      coalesce (``linger_s > 0`` only), or ``("idle", None)``.

    ``linger_s`` is an OPTIONAL coalescing window bounding how long a
    sub-top batch may wait for company, measured from the OLDEST queued
    request's arrival; the default 0 is pure continuous batching (dispatch
    the instant a lane frees).  Note the asymmetry with the retired
    deadline batcher: linger only ever delays a batch that has a free lane
    AND spare bucket room, never an admitted request behind a busy fleet.

    Starvation-freedom is structural: formation always starts at the queue
    head, so the oldest request is in EVERY dispatched batch until served
    — a younger request can never jump it.
    """

    def __init__(self, buckets, queue_bound=256, linger_s=0.0):
        self.buckets = tuple(int(b) for b in buckets)
        if not self.buckets or list(self.buckets) != sorted(set(self.buckets)) \
                or self.buckets[0] < 1:
            raise UserException(
                "ContinuousPolicy wants a sorted positive bucket ladder, got %r"
                % (buckets,)
            )
        self.top = self.buckets[-1]
        self.queue_bound = int(queue_bound)
        if self.queue_bound < 1:
            raise UserException("queue_bound must be >= 1")
        self.linger_s = float(linger_s)
        if self.linger_s < 0.0:
            raise UserException("linger_s must be >= 0")

    def admit(self, queued_rows, new_rows):
        """Shed decision for a ``new_rows``-row request arriving over a
        ``queued_rows``-deep queue.  True = admit, False = shed (429)."""
        if new_rows < 1:
            raise UserException("Empty request")
        if new_rows > self.top:
            raise UserException(
                "Request of %d rows exceeds the ladder top %d; split it "
                "client-side" % (new_rows, self.top)
            )
        return queued_rows == 0 or queued_rows + new_rows <= self.queue_bound

    def plan(self, pending, now):
        """One scheduling decision for one free lane.

        ``pending``: sequence of ``(nb_rows, enqueued_at)`` oldest first.
        Returns ``("dispatch", (nb_requests, bucket))`` /
        ``("wait", due_at)`` / ``("idle", None)``.
        """
        if not pending:
            return ("idle", None)
        take, rows = 0, 0
        for nb_rows, _ in pending:
            if rows + nb_rows > self.top:
                break
            take += 1
            rows += nb_rows
        # take >= 1 always: admit() bounded every request at the ladder top
        if self.linger_s > 0.0 and rows < self.top:
            due_at = pending[0][1] + self.linger_s
            if now < due_at:
                return ("wait", due_at)
        return ("dispatch", (take, choose_bucket(rows, self.buckets)))


class ContinuousBatcher:
    """Lane pool + queue in front of an inference runner.

    Args:
      runner: ``(rows) -> dict`` — typically ``InferenceEngine.predict``.
        Leading-axis-``k`` ndarray values are split per request; other
        values (disagreement vectors, bucket/weights-step scalars) are
        shared by every request in the batch.
      buckets: the engine's bucket ladder (sorted ascending); the top
        bounds a single request's rows.
      queue_bound: queued-row limit beyond which ``submit`` sheds.
      nb_lanes: initial dispatch-lane count (in-flight batches); resized
        live by ``set_lanes`` within [1, ``max_lanes``].
      max_lanes: hard lane ceiling (default ``nb_lanes``); the
        autoscaler's capacity range.
      linger_s: optional coalescing window (see :class:`ContinuousPolicy`).
      clock: injectable monotonic clock (tests).
      on_batch: ``fn(rows, requests, latency_s, output)`` after each batch.
    """

    #: result keys never split per request even when their leading
    #: dimension happens to equal the batch's row count
    SHARED_KEYS = ("disagreement", "bucket", "weights_step", "active_replicas")

    def __init__(self, runner, buckets, queue_bound=256, nb_lanes=1,
                 max_lanes=None, linger_s=0.0, clock=time.monotonic,
                 on_batch=None, shared_keys=SHARED_KEYS):
        self.runner = runner
        self.policy = ContinuousPolicy(buckets, queue_bound=queue_bound,
                                       linger_s=linger_s)
        self.max_lanes = int(max_lanes) if max_lanes is not None else int(nb_lanes)
        if not 1 <= int(nb_lanes) <= self.max_lanes:
            raise UserException(
                "need 1 <= nb_lanes (%d) <= max_lanes (%d)"
                % (int(nb_lanes), self.max_lanes)
            )
        self.clock = clock
        self.on_batch = on_batch
        self.shared_keys = frozenset(shared_keys)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue = []
        self._queued_rows = 0
        self._closed = False
        self._target_lanes = 0
        self._lane_threads = {}
        self._in_flight = 0
        self.batch_count = 0
        self.served_rows = 0
        self.shed_count = 0
        self.cancelled_count = 0
        #: occupancy of the last dispatched batch: (rows, bucket)
        self.last_occupancy = (0, self.policy.top)
        self.set_lanes(nb_lanes)

    # ------------------------------------------------------------------ #
    # producer side

    def submit(self, rows):
        """Enqueue ``rows`` ((k, *sample) array, k >= 1); returns a
        :class:`Ticket`.  Sheds with :class:`LoadShed` over the bound."""
        rows = np.asarray(rows)
        k = int(rows.shape[0]) if rows.ndim else 0
        with self._cond:
            if self._closed:
                raise RuntimeError("ContinuousBatcher is closed")
            if not self.policy.admit(self._queued_rows, k):
                self.shed_count += 1
                trace.instant("serve.shed", cat="serve", rows=k,
                              queued_rows=self._queued_rows)
                raise LoadShed(
                    "queue at %d/%d rows; request of %d rows shed"
                    % (self._queued_rows, self.policy.queue_bound, k)
                )
            pending = _Pending(rows, self.clock())
            self._queue.append(pending)
            self._queued_rows += k
            self._cond.notify_all()
        trace.instant("serve.enqueue", cat="serve", rows=k)
        return Ticket(self, pending)

    def _cancel(self, pending):
        """Drop a still-queued request (timed-out/cancelled Ticket)."""
        with self._cond:
            if pending in self._queue:
                self._queue.remove(pending)
                self._queued_rows -= pending.rows.shape[0]
                self.cancelled_count += 1
                removed = True
            else:
                removed = False
        if removed:
            pending.finish(error=TimeoutError(
                "request cancelled after wait timeout"
            ))
        return removed

    @property
    def queue_depth(self):
        """Queued rows awaiting dispatch (the backpressure signal)."""
        with self._lock:
            return self._queued_rows

    @property
    def in_flight(self):
        """Batches currently dispatched on a lane."""
        with self._lock:
            return self._in_flight

    @property
    def nb_lanes(self):
        """The current dispatch-lane target (the autoscaled pool size)."""
        with self._lock:
            return self._target_lanes

    # ------------------------------------------------------------------ #
    # lane pool

    def set_lanes(self, nb_lanes):
        """Resize the dispatch-lane pool live, within [1, max_lanes].

        Scale-up spawns the missing lane threads; scale-down lets excess
        lanes finish their current batch and exit — in-flight work is
        never interrupted.  Returns the new target."""
        nb_lanes = int(nb_lanes)
        if not 1 <= nb_lanes <= self.max_lanes:
            raise UserException(
                "lane count must lie in [1, %d], got %d"
                % (self.max_lanes, nb_lanes)
            )
        with self._cond:
            if self._closed:
                raise RuntimeError("ContinuousBatcher is closed")
            self._target_lanes = nb_lanes
            for index in range(nb_lanes):
                if index not in self._lane_threads:
                    thread = threading.Thread(
                        target=self._lane, args=(index,), daemon=True,
                        name="serve-lane-%d" % index,
                    )
                    self._lane_threads[index] = thread
                    thread.start()
            self._cond.notify_all()
        return nb_lanes

    def _lane(self, index):
        try:
            while True:
                with self._cond:
                    batch = None
                    while batch is None:
                        if self._closed or index >= self._target_lanes:
                            # deregister INSIDE the locked exit decision: a
                            # concurrent scale-up must not see this zombie
                            # entry and skip respawning the lane
                            self._deregister_lane(index)
                            return
                        kind, arg = self.policy.plan(
                            [(p.rows.shape[0], p.enqueued_at)
                             for p in self._queue],
                            self.clock(),
                        )
                        if kind == "dispatch":
                            nb_requests, bucket = arg
                            batch = self._queue[:nb_requests]
                            del self._queue[:nb_requests]
                            self._queued_rows -= sum(
                                p.rows.shape[0] for p in batch
                            )
                            self._in_flight += 1
                        elif kind == "wait":
                            self._cond.wait(max(0.0, arg - self.clock()))
                        else:
                            self._cond.wait()
                try:
                    self._run_batch(batch, bucket)
                finally:
                    with self._cond:
                        self._in_flight -= 1
                        # a freed lane is the wake signal continuous
                        # batching is named for: whoever queued meanwhile
                        # joins the next dispatch right now
                        self._cond.notify_all()
        finally:
            with self._cond:
                self._deregister_lane(index)
                self._cond.notify_all()

    def _deregister_lane(self, index):
        """Drop this thread's own pool registration (caller holds the
        lock).  Identity-checked: after a scale-down/up cycle the index may
        already belong to a FRESH lane thread, whose entry must survive the
        old thread's exit path."""
        if self._lane_threads.get(index) is threading.current_thread():
            self._lane_threads.pop(index, None)

    def _run_batch(self, batch, bucket):
        rows = (np.concatenate([p.rows for p in batch])
                if len(batch) > 1 else batch[0].rows)
        started = self.clock()
        try:
            with trace.span("serve.batch", cat="serve",
                            rows=int(rows.shape[0]), requests=len(batch)):
                out = self.runner(rows)
        except Exception as exc:  # surfaced per ticket, the lane survives
            for pending in batch:
                pending.finish(error=exc)
            return
        k = rows.shape[0]
        offset = 0
        for pending in batch:
            span = pending.rows.shape[0]
            result = {}
            for name, value in out.items():
                if (name not in self.shared_keys
                        and isinstance(value, np.ndarray)
                        and value.ndim >= 1 and value.shape[0] == k):
                    result[name] = value[offset:offset + span]
                else:
                    result[name] = value  # batch-shared extras
            offset += span
            pending.finish(result=result)
        with self._lock:
            self.batch_count += 1
            self.served_rows += k
            self.last_occupancy = (k, bucket)
        if self.on_batch is not None:
            self.on_batch(rows=k, requests=len(batch),
                          latency_s=self.clock() - started, output=out)

    # ------------------------------------------------------------------ #
    # lifecycle

    def close(self, timeout=5.0):
        """Stop every lane; queued requests are failed, not served.
        Idempotent; in-flight batches finish first."""
        with self._cond:
            already = self._closed
            self._closed = True
            leftovers, self._queue = self._queue, []
            self._queued_rows = 0
            threads = list(self._lane_threads.values())
            self._cond.notify_all()
        for pending in leftovers:
            pending.finish(error=RuntimeError("ContinuousBatcher closed"))
        if not already:
            for thread in threads:
                thread.join(timeout)

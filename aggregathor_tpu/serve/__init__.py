"""Byzantine-robust batched inference serving.

The inference layer the ROADMAP's "serve heavy traffic" north star asks for:
trained checkpoints (``obs/checkpoint.py`` restore, authenticator honored)
answer prediction requests through ONE compiled apply path.

- ``engine``:  :class:`InferenceEngine` — a fixed power-of-two **bucket
  ladder** of padded batch shapes (zero steady-state recompiles, the chaos
  scheduler's compile discipline applied to serving) and R-way **replicated
  robust inference**: replica logits stacked ``(R, batch, classes)`` and
  reduced by the training GARs (``gars/``) with the NaN-last convention, so
  a crashed/corrupted replica is absorbed exactly like a Byzantine worker's
  gradient row; per-replica disagreement scores feed quarantine-style
  flagging.
- ``batcher``: :class:`MicroBatcher` — deadline micro-batching (dispatch at
  ``max_latency`` OR a full bucket), bounded queue with explicit
  **load-shedding** (:class:`LoadShed` -> HTTP 429).
- ``server``:  :class:`InferenceServer` — stdlib ``ThreadingHTTPServer``
  exposing ``/predict``, ``/healthz`` and ``/metrics`` (queue depth, batch
  occupancy, p50/p95/p99, shed count, per-replica disagreement), metrics
  mirrored as ``obs/summaries`` JSONL events.
- ``campaign``: the replica-fault resilience harness (fault modes from
  ``chaos/replica_faults.py``) proving median-of-replicas serves at the
  clean bar while plain averaging degrades.

CLI: ``python -m aggregathor_tpu.cli.serve --ckpt-dir ... --experiment ...
--replicas R --gar median`` (see ``cli/serve.py``; docs/serving.md).
"""

from .batcher import LoadShed, MicroBatcher, Ticket  # noqa: F401
from .engine import (  # noqa: F401
    InferenceEngine,
    bucket_ladder,
    choose_bucket,
    restore_params,
)
from .server import InferenceServer  # noqa: F401

"""Byzantine-robust serving at sustained concurrency (serve/ v2).

The inference layer the ROADMAP's "serve heavy traffic" north star asks
for: trained checkpoints (``obs/checkpoint.py`` restore, authenticator
honored) answer prediction requests through ONE compiled apply path, as
four composable subsystems (docs/serving.md — the NET-SA framing:
front end, scheduler, pool and weight pipeline are architecture, not one
blocking handler):

- ``engine``:     :class:`InferenceEngine` — a fixed power-of-two **bucket
  ladder** of padded batch shapes (zero steady-state recompiles) and R-way
  **replicated robust inference**: replica logits reduced by the training
  GARs (``gars/``) with the NaN-last convention; per-replica disagreement
  scores; a traced **active-replica mask** (pool scaling spends the
  declared-f budget) and an atomic **hot weight swap** tagged with the
  served ``weights_step`` — both on the same compiled executables.
- ``continuous``: :class:`ContinuousBatcher` — continuous (in-flight)
  batching on the bucket ladder: requests join the next dispatch the
  moment a lane frees, formation is a PURE synthetic-clock
  :class:`ContinuousPolicy`, backpressure stays explicit
  (:class:`LoadShed` -> HTTP 429).
- ``frontend``:   :class:`InferenceServer` — ONE asyncio event-loop thread
  serving ``/predict`` / ``/healthz`` / ``/metrics`` / ``/status``
  (400/429/504 contract kept; in-flight requests cost a coroutine, not a
  thread).
- ``autoscale``:  registry-driven pool scaling (queue depth, p99, shed
  rate -> hysteresis policy) over dispatch lanes and vote replicas, with
  the declared-f feasibility floor.
- ``weights``:    :class:`CheckpointWatcher` — the zero-downtime weight
  pipeline following a training run's snapshot directory (custody
  verified, zero recompiles, zero dropped requests).
- ``campaign``:   the replica-fault resilience harness (fault modes from
  ``chaos/replica_faults.py``) proving median-of-replicas serves at the
  clean bar while plain averaging degrades — now through the scheduler.
- ``router``:     the traffic plane — :class:`FleetRouter` puts N of these
  processes behind ONE admission port: a pure :class:`RoutingPolicy`
  (least-in-flight, step-pin eligibility), fleet-decision shed, drain
  re-routing, retry-once on a mid-flight backend death, and a
  fleet-consistent ``weights_step`` guarantee (no client ever observes
  the step go backwards across replicas).  CLI:
  ``python -m aggregathor_tpu.cli.router``.

CLI: ``python -m aggregathor_tpu.cli.serve --ckpt-dir ... --experiment ...
--replicas R --gar median`` (see ``cli/serve.py``; docs/serving.md).
"""

from .autoscale import (  # noqa: F401
    AutoscaleConfig,
    AutoscalePolicy,
    CapacityLadder,
    PoolAutoscaler,
)
from .continuous import (  # noqa: F401
    ContinuousBatcher,
    ContinuousPolicy,
    LoadShed,
    Ticket,
)
from .engine import (  # noqa: F401
    InferenceEngine,
    bucket_ladder,
    choose_bucket,
    restore_params,
)
from .frontend import InferenceServer  # noqa: F401
from .router import (  # noqa: F401
    CAUSAL_HEADER,
    BackendView,
    FleetRouter,
    RouterServer,
    RoutingPolicy,
)
from .weights import CheckpointWatcher  # noqa: F401

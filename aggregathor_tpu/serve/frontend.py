"""Asyncio serving front end: sustained concurrency without thread-per-request.

PR 3's ``ThreadingHTTPServer`` spent one OS thread per open connection —
fine for a smoke burst, hopeless for the ROADMAP's sustained-traffic target
(a thousand in-flight requests is a thousand blocked threads fighting the
GIL just to sleep on a ticket).  The v2 front end is ONE event-loop thread:
a minimal asyncio HTTP/1.1 server parses requests, submits rows to the
continuous scheduler (``serve/continuous.py``) without blocking, and awaits
each ticket through a completion callback bridged onto the loop — in-flight
requests cost a parked coroutine, not a thread.  All compute still happens
on the scheduler's dispatch lanes inside XLA; the loop thread only parses
and serializes JSON.

The PR-3 response contract is kept verbatim:

- ``POST /predict``  → 200 with predictions/disagreement/bucket (now plus
  ``weights_step`` + ``active_replicas``); **400** malformed input; **429**
  + ``{"error": "shed"}`` on explicit :class:`~.continuous.LoadShed`;
  **504** when the batch misses ``request_timeout_s`` (the ticket is
  CANCELLED — lanes never run dead work); **500** on an engine failure
  (the server survives).
- ``GET /healthz``   liveness + replica/custody summary.
- ``GET /metrics``   Prometheus text exposition of the ONE process-wide
  registry (``obs/metrics.py``), like the training exporter's — one scrape
  config covers both.  The historical JSON gauge snapshot stays reachable
  via the EXPLICIT ``?format=json``.  (Deprecation note: before PR 16 the
  bare path defaulted to the JSON payload while training served text —
  the format split the fleet collector had to special-case; scripts that
  want JSON must now say so.)
- ``GET /status``    the serving twin of the live trainer exporter's
  ``/status`` (``obs/live.py``): weights step, active replicas, lanes,
  queue/in-flight plus the LIVE pressure fields the fleet router
  (``serve/router.py``) routes on — queue bound, per-scrape shed delta,
  at-ceiling, draining — so routing never parses Prometheus text on the
  hot path.

:class:`InferenceServer` is the composite the CLI and tests drive: engine +
continuous scheduler + this front end + the registry instruments, with the
same lifecycle surface as v1 (``serve_background`` / ``shutdown_all``).
"""

import asyncio
import json
import threading
import urllib.parse

import numpy as np

from ..obs import LatencyHistogram
from ..obs import events as obs_events
from ..obs import metrics as obs_metrics
from ..obs import trace
from ..utils import UserException, info
from .continuous import ContinuousBatcher, LoadShed

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    429: "Too Many Requests", 500: "Internal Server Error",
    504: "Gateway Timeout",
}

#: request bodies above this are refused outright (a ladder-top batch of
#: any supported experiment serializes far below it)
MAX_BODY_BYTES = 64 * 1024 * 1024


def _jsonable(value):
    value = float(value)
    return value if np.isfinite(value) else None  # strict JSON: inf/NaN -> null


class InferenceServer:
    """The serving process: asyncio front end + continuous scheduler + engine.

    ``port=0`` binds an ephemeral port (``serve_background`` returns the
    bound address).  ``summaries`` is an optional ``SummaryWriter``;
    ``flag_threshold`` marks a replica suspect when its latest disagreement
    exceeds it (non-finite scores are always suspect; retired replicas are
    reported as inactive, never suspect).  ``registry`` is the metrics
    registry to export through (default the process-wide
    ``obs.metrics.REGISTRY``); ``shutdown_all`` unregisters this server's
    serve_* instruments so a successor starts from fresh counts.

    ``lanes``/``max_lanes`` size the scheduler's dispatch-lane pool (the
    autoscaler's capacity range, ``serve/autoscale.py``); ``linger_s`` is
    the optional sub-top coalescing window (0 = pure continuous batching).
    """

    def __init__(self, engine, host="127.0.0.1", port=0, queue_bound=256,
                 lanes=1, max_lanes=None, linger_s=0.0, summaries=None,
                 request_timeout_s=60.0, flag_threshold=None, clock=None,
                 registry=None, custody_verified=None):
        import time

        self.engine = engine
        # Chain-of-custody verdict of the served checkpoints (cli/serve.py):
        # True = every replica's lineage manifest verified, False = at least
        # one unsigned/unverified restore was explicitly allowed through,
        # None = no --session-secret (verification not attempted).  Updated
        # on every hot swap (set_custody_verified), surfaced by /healthz.
        self.custody_verified = custody_verified
        self.clock = clock if clock is not None else time.monotonic
        self.summaries = summaries
        self.request_timeout_s = float(request_timeout_s)
        self.flag_threshold = flag_threshold
        self.registry = registry if registry is not None else obs_metrics.REGISTRY
        self._host, self._port = host, int(port)
        self._lock = threading.Lock()
        self._thread = None
        self._ready = None
        self._startup_error = None
        self._aio_loop = None
        self._aio_stop = None
        self._addr = None
        self._open_connections = 0
        self.shed_rows = 0
        self.draining = False
        self._status_shed_seen = 0
        self._last_disagreement = [0.0] * engine.nb_replicas
        self._metric_names = [
            "serve_request_latency_seconds", "serve_shed_requests_total",
            "serve_shed_rows_total", "serve_batches_total",
            "serve_served_rows_total", "serve_replica_disagreement",
            "serve_queue_rows", "serve_queue_bound", "serve_compile_count",
            "serve_batch_occupancy_fill", "serve_suspect_replica_count",
            "serve_dispatch_lanes", "serve_inflight_batches",
            "serve_active_replicas", "serve_weights_step",
            "serve_cancelled_requests_total", "serve_open_connections",
            "serve_request_timeouts_total",
        ]
        # Registry-backed instruments; ``latency`` keeps the LatencyHistogram
        # API (record/percentiles/count), so the JSON payload is unchanged.
        self.latency = self.registry.histogram(
            "serve_request_latency_seconds", "End-to-end /predict latency"
        )
        self._m_shed_requests = self.registry.counter(
            "serve_shed_requests_total", "Requests rejected by load-shedding (429)"
        )
        self._m_shed_rows = self.registry.counter(
            "serve_shed_rows_total", "Rows rejected by load-shedding"
        )
        self._m_batches = self.registry.counter(
            "serve_batches_total", "Batches dispatched by the scheduler"
        )
        self._m_served_rows = self.registry.counter(
            "serve_served_rows_total", "Rows served through dispatched batches"
        )
        self._m_timeouts = self.registry.counter(
            "serve_request_timeouts_total", "Requests that missed the request "
            "timeout (504; their queued rows were cancelled)"
        )
        self._m_disagreement = self.registry.gauge(
            "serve_replica_disagreement",
            "Latest per-replica disagreement score", labelnames=("replica",),
        )
        self.scheduler = ContinuousBatcher(
            engine.predict,
            buckets=engine.buckets,
            queue_bound=queue_bound,
            nb_lanes=lanes,
            max_lanes=max_lanes,
            linger_s=linger_s,
            on_batch=self._on_batch,
        )
        # Live views, read at scrape time (no writer loop to go stale).
        self.registry.gauge(
            "serve_queue_rows", "Rows queued awaiting dispatch"
        ).set_function(lambda: self.scheduler.queue_depth)
        self.registry.gauge(
            "serve_queue_bound", "Queued-row bound beyond which requests shed"
        ).set_function(lambda: self.scheduler.policy.queue_bound)
        self.registry.gauge(
            "serve_compile_count", "Executables compiled (one per bucket shape)"
        ).set_function(lambda: self.engine.compile_count)
        self.registry.gauge(
            "serve_batch_occupancy_fill", "Row fill of the last dispatched batch"
        ).set_function(
            lambda: (self.scheduler.last_occupancy[0] / self.scheduler.last_occupancy[1])
            if self.scheduler.last_occupancy[1] else 0.0
        )
        self.registry.gauge(
            "serve_suspect_replica_count", "Replicas currently flagged suspect"
        ).set_function(lambda: len(self.suspect_replicas()))
        self.registry.gauge(
            "serve_dispatch_lanes", "Dispatch lanes (concurrent in-flight "
            "batches) — the autoscaled pool size"
        ).set_function(lambda: self.scheduler.nb_lanes)
        self.registry.gauge(
            "serve_inflight_batches", "Batches currently in flight on a lane"
        ).set_function(lambda: self.scheduler.in_flight)
        self.registry.gauge(
            "serve_active_replicas", "Replicas currently voting (pool scale)"
        ).set_function(lambda: len(self.engine.active_replicas))
        self.registry.gauge(
            "serve_weights_step", "Training step of the served weights "
            "(-1 when the checkpoint carried none)"
        ).set_function(
            lambda: -1 if self.engine.weights_step is None
            else self.engine.weights_step
        )
        self.registry.gauge(
            "serve_cancelled_requests_total", "Requests cancelled after a "
            "wait timeout (their queued rows were dropped)"
        ).set_function(lambda: self.scheduler.cancelled_count)
        self.registry.gauge(
            "serve_open_connections", "Open front-end connections"
        ).set_function(self._connections)

    def _connections(self):
        with self._lock:
            return self._open_connections

    # ------------------------------------------------------------------ #
    # request plumbing

    def parse_inputs(self, request):
        """``{"inputs": [...]}`` -> (k, *sample_shape) float32 rows.  Rows may
        arrive shaped or flattened; both forms are reshaped and validated
        against the experiment's sample shape."""
        inputs = request.get("inputs")
        if inputs is None:
            raise UserException('Request body wants {"inputs": [[...], ...]}')
        rows = np.asarray(inputs, np.float32)
        shape = self.engine.sample_shape
        if rows.ndim == 1:  # one flat sample
            rows = rows[None]
        if rows.ndim == 2 and rows.shape[1] == int(np.prod(shape)):
            rows = rows.reshape((rows.shape[0],) + shape)
        if rows.ndim == len(shape):  # one shaped sample
            rows = rows[None]
        if rows.ndim != len(shape) + 1 or tuple(rows.shape[1:]) != shape:
            raise UserException(
                "Input rows of shape %r do not match sample shape %r (flat %d also accepted)"
                % (tuple(rows.shape[1:]), shape, int(np.prod(shape)))
            )
        return rows

    def _on_batch(self, rows, requests, latency_s, output):
        disagreement = np.atleast_1d(np.asarray(output.get("disagreement", [])))
        self._m_batches.inc()
        self._m_served_rows.inc(int(rows))
        with self._lock:
            if disagreement.size == self.engine.nb_replicas:
                self._last_disagreement = [float(v) for v in disagreement]
                for index, score in enumerate(self._last_disagreement):
                    # retired replicas read NaN: freeze their gauge at 0
                    # rather than exporting a NaN sample
                    self._m_disagreement.labels(replica=str(index)).set(
                        0.0 if np.isnan(score)
                        else (score if np.isfinite(score) else float("inf"))
                    )
        if self.summaries is not None:
            self.summaries.event(self.scheduler.batch_count, "serve_batch", {
                "rows": int(rows),
                "requests": int(requests),
                "bucket": int(output.get("bucket", 0)),
                "batch_latency_ms": float(latency_s) * 1e3,
                "weights_step": output.get("weights_step"),
                "disagreement": [_jsonable(v) for v in disagreement],
            })

    def note_shed(self, rows, detail):
        self._m_shed_requests.inc()
        self._m_shed_rows.inc(int(rows))
        with self._lock:
            self.shed_rows += int(rows)
        if self.summaries is not None:
            self.summaries.event(self.scheduler.batch_count, "serve_shed", {
                "rows": int(rows),
                "queue_depth": self.scheduler.queue_depth,
                "detail": detail,
            })

    # ------------------------------------------------------------------ #
    # introspection payloads

    def last_disagreement(self):
        """Latest per-replica disagreement snapshot (NaN = retired) — the
        autoscaler's retire-most-suspect-first ordering reads it."""
        with self._lock:
            return list(self._last_disagreement)

    def suspect_replicas(self):
        """ACTIVE replica indices whose latest disagreement flags them:
        non-finite always; above ``flag_threshold`` when one is configured.
        Retired replicas (disagreement NaN) are inactive, not suspect."""
        with self._lock:
            scores = list(self._last_disagreement)
        suspects = []
        for index, score in enumerate(scores):
            if np.isnan(score):
                continue  # retired by the autoscaler: scaled out, not faulty
            if not np.isfinite(score):
                suspects.append(index)
            elif self.flag_threshold is not None and score > self.flag_threshold:
                suspects.append(index)
        return suspects

    def set_custody_verified(self, verdict):
        """Update the provenance verdict after a hot swap."""
        self.custody_verified = verdict

    def begin_drain(self):
        """Mark this process draining: ``/status`` reports it so the fleet
        router re-routes NEW traffic while in-flight (and any stragglers
        that race the scrape window) keep being served.  The caller
        (cli/serve.py's SIGTERM path) waits for quiescence and exits."""
        with self._lock:
            self.draining = True

    def is_quiescent(self):
        """True when nothing is queued or in flight — the drain exit gate."""
        return self.scheduler.queue_depth == 0 and self.scheduler.in_flight == 0

    def health_payload(self):
        return {
            "status": "ok",
            "replicas": self.engine.nb_replicas,
            "active_replicas": self.engine.active_replicas,
            "vote": type(self.engine.gar).__name__ if self.engine.gar else None,
            "buckets": list(self.engine.buckets),
            "suspect_replicas": self.suspect_replicas(),
            "custody_verified": self.custody_verified,
            "weights_step": self.engine.weights_step,
        }

    def status_payload(self):
        """The serving ``/status`` body — the live handles the smoke's
        swap/autoscale legs poll between requests, and the pressure
        surface the fleet router (``serve/router.py``) routes on.

        ``shed_delta`` is the number of shed REQUESTS since the previous
        ``/status`` read — per-scrape semantics for the one routing
        scraper (a second concurrent scraper would split the deltas; it
        should diff the cumulative ``shed_count`` instead).
        ``at_ceiling`` reads the capacity truth without requiring the
        autoscaler: the lane pool cannot grow further."""
        sheds = self.scheduler.shed_count
        with self._lock:
            shed_delta = sheds - self._status_shed_seen
            self._status_shed_seen = sheds
            draining = self.draining
        return {
            "weights_step": self.engine.weights_step,
            "active_replicas": self.engine.active_replicas,
            "lanes": self.scheduler.nb_lanes,
            "max_lanes": self.scheduler.max_lanes,
            "at_ceiling": self.scheduler.nb_lanes >= self.scheduler.max_lanes,
            "in_flight": self.scheduler.in_flight,
            "queue_depth": self.scheduler.queue_depth,
            "queue_bound": self.scheduler.policy.queue_bound,
            "shed_count": sheds,
            "shed_delta": shed_delta,
            "draining": draining,
            "batch_count": self.scheduler.batch_count,
            "compile_count": self.engine.compile_count,
            "custody_verified": self.custody_verified,
        }

    def metrics_payload(self):
        tail = self.latency.percentiles()
        occupancy_rows, occupancy_cap = self.scheduler.last_occupancy
        with self._lock:
            disagreement = [_jsonable(v) for v in self._last_disagreement]
            shed_rows = self.shed_rows
        return {
            "queue_depth": self.scheduler.queue_depth,
            "queue_bound": self.scheduler.policy.queue_bound,
            "batch_count": self.scheduler.batch_count,
            "served_rows": self.scheduler.served_rows,
            "shed_count": self.scheduler.shed_count,
            "shed_rows": shed_rows,
            "cancelled_count": self.scheduler.cancelled_count,
            "in_flight": self.scheduler.in_flight,
            "lanes": self.scheduler.nb_lanes,
            "max_lanes": self.scheduler.max_lanes,
            "active_replicas": self.engine.active_replicas,
            "weights_step": self.engine.weights_step,
            "batch_occupancy": {
                "rows": occupancy_rows, "cap": occupancy_cap,
                "fill": (occupancy_rows / occupancy_cap) if occupancy_cap else 0.0,
            },
            "latency_ms": {
                name: (tail[name] * 1e3 if tail else None)
                for name, _ in LatencyHistogram.POINTS
            },
            "request_count": self.latency.count,
            "per_replica_disagreement": disagreement,
            "suspect_replicas": self.suspect_replicas(),
            "compile_count": self.engine.compile_count,
            "nb_buckets": len(self.engine.buckets),
        }

    def prometheus_payload(self):
        """Text exposition of the whole registry (``/metrics?format=
        prometheus``) — training/serve metrics that share the process-wide
        registry scrape together."""
        return self.registry.render_prometheus()

    # ------------------------------------------------------------------ #
    # the asyncio front end

    async def _handle_predict(self, body):
        started = self.clock()
        try:
            request = json.loads(body or b"{}")
            if not isinstance(request, dict):
                raise UserException("Request body must be a JSON object")
            rows = self.parse_inputs(request)
        except (ValueError, TypeError, UserException) as exc:
            return 400, {"error": str(exc)}
        try:
            ticket = self.scheduler.submit(rows)
        except LoadShed as exc:
            self.note_shed(rows.shape[0], str(exc))
            return 429, {"error": "shed", "detail": str(exc)}
        except (ValueError, RuntimeError, UserException) as exc:
            return 400, {"error": str(exc)}
        loop = asyncio.get_running_loop()
        future = loop.create_future()

        def deliver(pending):
            # runs on the completing dispatch lane: hop onto the loop; the
            # future may already be gone (request timed out and cancelled)
            def resolve():
                if future.done():
                    return
                if pending.error is not None:
                    future.set_exception(pending.error)
                else:
                    future.set_result(pending.result)
            try:
                loop.call_soon_threadsafe(resolve)
            except RuntimeError:
                pass  # loop already shut down: nobody is waiting

        ticket.add_done_callback(deliver)
        try:
            result = await asyncio.wait_for(future, self.request_timeout_s)
        except (asyncio.TimeoutError, TimeoutError):
            ticket.cancel()
            self._m_timeouts.inc()
            return 504, {"error": "inference batch did not complete in time"}
        except Exception as exc:  # inference failure: surfaced, server lives
            return 500, {"error": str(exc)}
        self.latency.record(self.clock() - started)
        return 200, {
            "predictions": [int(p) for p in result["predictions"]],
            "disagreement": [_jsonable(v)
                             for v in np.atleast_1d(result["disagreement"])],
            "bucket": int(result["bucket"]),
            "weights_step": result.get("weights_step"),
            "active_replicas": result.get("active_replicas"),
        }

    def _wants_prometheus(self, query, headers):
        """Format negotiation: explicit ``?format=`` wins; otherwise the
        bare path serves Prometheus text — the SAME default as the
        training exporter (obs/live.py), so one scrape config covers both.
        An ``Accept`` header asking for JSON (and not text/plain) still
        negotiates the JSON snapshot.  (The historical bare-path JSON
        default is retired; say ``?format=json`` explicitly.)"""
        fmt = urllib.parse.parse_qs(query).get("format", [None])[0]
        if fmt is not None:
            if fmt not in ("json", "prometheus"):
                raise UserException(
                    "unknown metrics format %r (json or prometheus)" % fmt
                )
            return fmt == "prometheus"
        accept = headers.get("accept", "")
        return not ("application/json" in accept and "text/plain" not in accept)

    async def _route(self, method, target, headers, body):
        """-> (code, content_type, body_str)."""
        parsed = urllib.parse.urlsplit(target)
        if method == "POST" and parsed.path == "/predict":
            trace.instant("serve.request", cat="serve", bytes=len(body))
            code, payload = await self._handle_predict(body)
            # the causal-plane echo (docs/observability.md): a valid
            # X-Causal-Id token (the router's journal-event reference)
            # rides back in the response, so the caller can join this
            # answer to the routing decision that produced it; a garbled
            # token is dropped, never a request failure
            token = headers.get("x-causal-id")
            if token is not None and isinstance(payload, dict):
                try:
                    obs_events.parse_cause(token)
                except ValueError:
                    pass
                else:
                    payload = dict(payload, causal_id=token)
            return code, "application/json", json.dumps(payload)
        if method == "GET" and parsed.path == "/healthz":
            return 200, "application/json", json.dumps(self.health_payload())
        if method == "GET" and parsed.path == "/status":
            return 200, "application/json", json.dumps(self.status_payload())
        if method == "GET" and parsed.path == "/metrics":
            try:
                prometheus = self._wants_prometheus(parsed.query, headers)
            except UserException as exc:
                return 400, "application/json", json.dumps({"error": str(exc)})
            if prometheus:
                return (200, obs_metrics.PROMETHEUS_CONTENT_TYPE,
                        self.prometheus_payload())
            return 200, "application/json", json.dumps(self.metrics_payload())
        return 404, "application/json", json.dumps(
            {"error": "unknown path %r" % parsed.path}
        )

    async def _handle_client(self, reader, writer):
        with self._lock:
            self._open_connections += 1
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                parts = line.decode("latin1").strip().split()
                if len(parts) != 3:
                    return  # not HTTP: drop the connection
                method, target, version = parts
                headers = {}
                while True:
                    header = await reader.readline()
                    if header in (b"\r\n", b"\n", b""):
                        break
                    name, _, value = header.decode("latin1").partition(":")
                    headers[name.strip().lower()] = value.strip()
                # Drain the body FIRST, before any reply: under keep-alive
                # an unread body would be parsed as the next request line.
                try:
                    length = int(headers.get("content-length", "0") or 0)
                except ValueError:
                    return
                refused_body = length < 0 or length > MAX_BODY_BYTES
                if refused_body:
                    code, ctype, payload = 400, "application/json", json.dumps(
                        {"error": "unacceptable Content-Length %d" % length}
                    )
                else:
                    body = await reader.readexactly(length) if length else b""
                    code, ctype, payload = await self._route(
                        method, target, headers, body
                    )
                # a refused body was never drained: the connection MUST
                # close, or its bytes would be parsed as the next request
                keep = (version == "HTTP/1.1"
                        and headers.get("connection", "").lower() != "close"
                        and not refused_body)
                payload = payload.encode()
                writer.write((
                    "HTTP/1.1 %d %s\r\n"
                    "Content-Type: %s\r\n"
                    "Content-Length: %d\r\n"
                    "Connection: %s\r\n\r\n"
                    % (code, _REASONS.get(code, "OK"), ctype, len(payload),
                       "keep-alive" if keep else "close")
                ).encode("latin1"))
                writer.write(payload)
                await writer.drain()
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError):
            return  # client went away mid-request
        finally:
            with self._lock:
                self._open_connections -= 1
            writer.close()

    async def _serve_main(self):
        server = await asyncio.start_server(
            self._handle_client, self._host, self._port
        )
        with self._lock:
            self._aio_loop = asyncio.get_running_loop()
            self._aio_stop = asyncio.Event()
            self._addr = server.sockets[0].getsockname()[:2]
            stop = self._aio_stop
        self._ready.set()
        async with server:
            await stop.wait()

    def _loop_main(self):
        try:
            asyncio.run(self._serve_main())
        except Exception as exc:
            with self._lock:
                self._startup_error = exc
            self._ready.set()

    # ------------------------------------------------------------------ #
    # lifecycle

    def serve_background(self):
        """Start the event-loop thread; returns the bound (host, port)."""
        with self._lock:
            if self._thread is not None:
                return self._addr
            self._ready = threading.Event()
            self._thread = threading.Thread(
                target=self._loop_main, daemon=True, name="serve-frontend"
            )
            thread = self._thread
        thread.start()
        if not self._ready.wait(30.0):
            raise UserException("serve front end failed to start in 30 s")
        with self._lock:
            error, addr = self._startup_error, self._addr
        if error is not None:
            raise error
        host, port = addr
        info("Serving on http://%s:%d (replicas=%d, vote=%s, buckets=%r, "
             "lanes=%d/%d)"
             % (host, port, self.engine.nb_replicas,
                type(self.engine.gar).__name__ if self.engine.gar else "none",
                list(self.engine.buckets), self.scheduler.nb_lanes,
                self.scheduler.max_lanes))
        return host, port

    @property
    def server_address(self):
        """(host, port) once ``serve_background`` returned (v1 surface)."""
        with self._lock:
            return self._addr if self._addr else (self._host, self._port)

    def shutdown_all(self):
        """Stop the event loop and the scheduler (idempotent), and
        unregister this server's serve_* instruments so a successor starts
        fresh and the gauge closures no longer keep the engine alive."""
        with self._lock:
            loop, stop = self._aio_loop, self._aio_stop
            thread, self._thread = self._thread, None
            self._aio_loop = self._aio_stop = None
        if loop is not None and stop is not None:
            try:
                loop.call_soon_threadsafe(stop.set)
            except RuntimeError:
                pass  # loop already gone
        if thread is not None:
            thread.join(5.0)
        self.scheduler.close()
        for name in self._metric_names:
            self.registry.unregister(name)

"""Zero-downtime weight pipeline: a checkpoint watcher over hot swaps.

PR 7 made the hot swap safe (``InferenceEngine.swap_replicas``: custody
verified, zero recompiles, atomic against in-flight forwards) but left it
MANUAL — an operator sending SIGHUP.  The watcher closes the loop the
ROADMAP asks for: serving FOLLOWS a concurrently-training run.  A daemon
thread polls the training run's snapshot directory; when a step newer than
the served one lands, the replicas re-restore through exactly the startup
path (chain-of-custody manifests re-verified fail-closed, poison specs
re-applied — a poisoned test replica STAYS poisoned across swaps, which
is what lets the load benchmark drive swaps against a faulty pool) and
swap in atomically.  Requests keep flowing throughout: a swap is one
host->device transfer behind the serving dispatches, never a recompile,
never a dropped ticket — and every response carries the ``weights_step``
its batch actually ran on, so "zero wrong-weight responses" is a checkable
claim (``benchmarks/serve_load.py``), not a promise.

A FAILED reload — custody violation, torn snapshot, vanished directory —
keeps the previous weights serving and is counted
(``serve_weight_swap_failures_total``), the PR-7 rule: a bad snapshot must
not take the service down.  ``SIGHUP`` remains as a manual trigger: the
CLI routes it to ``check_once(force=True)`` (re-restore even without a
newer step — the operator's "reload now").

The poll loop is deliberately dumb (no inotify: snapshot directories may
be network mounts) and everything decision-shaped is injectable —
``poll_steps``/``reload``/``clock`` — so tests drive the whole pipeline on
synthetic steps without a filesystem or a sleep.
"""

import threading
import time

from ..obs import events
from ..obs import metrics as obs_metrics
from ..obs import trace
from ..utils import UserException, info


class CheckpointWatcher:
    """Follows a snapshot stream and hot-swaps newer weights in.

    Args:
      poll_steps: zero-arg callable -> ascending iterable of available
        checkpoint steps (typically ``Checkpoints(...).steps``); exceptions
        count as a failed check and keep the current weights.
      reload: ``reload(step)`` restores the replica set at ``step`` and
        swaps it into the engine (the CLI closes over ``load_replicas`` +
        ``swap_replicas`` + custody bookkeeping); raising keeps the
        previous weights.
      served_step: the step currently serving (None = unknown — the first
        check swaps whatever is newest).
      interval_s: poll period for the background thread.
      registry: metrics registry (default process-wide):
        ``serve_weight_checks_total``, ``serve_weight_swaps_total``,
        ``serve_weight_swap_failures_total``.
      summaries: optional ``SummaryWriter`` — one tagged
        ``serve_weight_swap`` event per applied swap.
    """

    def __init__(self, poll_steps, reload, served_step=None, interval_s=2.0,
                 registry=None, summaries=None, clock=time.monotonic):
        if interval_s <= 0.0:
            raise UserException(
                "checkpoint watcher interval must be > 0 seconds"
            )
        self.poll_steps = poll_steps
        self.reload = reload
        self.interval_s = float(interval_s)
        self.summaries = summaries
        self.clock = clock
        self.registry = (registry if registry is not None
                         else obs_metrics.REGISTRY)
        self._lock = threading.Lock()
        self._served_step = served_step
        self._thread = None
        self._stop = threading.Event()
        self._metric_names = [
            "serve_weight_checks_total", "serve_weight_swaps_total",
            "serve_weight_swap_failures_total",
        ]
        self._c_checks = self.registry.counter(
            "serve_weight_checks_total", "Snapshot-directory polls"
        )
        self._c_swaps = self.registry.counter(
            "serve_weight_swaps_total", "Hot weight swaps applied"
        )
        self._c_failures = self.registry.counter(
            "serve_weight_swap_failures_total",
            "Reloads refused or failed (previous weights kept serving)"
        )

    @property
    def served_step(self):
        with self._lock:
            return self._served_step

    def check_once(self, force=False):
        """One poll: swap in the newest step when it beats the served one
        (or unconditionally re-restore with ``force`` — the SIGHUP path).
        Returns the newly-served step, or None when nothing changed.
        Serialized: concurrent calls (poll thread vs SIGHUP) queue on the
        watcher lock, so two reloads can never interleave."""
        with self._lock:
            self._c_checks.inc()
            try:
                steps = sorted(int(s) for s in self.poll_steps())
            except Exception as exc:
                self._c_failures.inc()
                info("checkpoint watcher poll failed (still serving step "
                     "%r): %s: %s"
                     % (self._served_step, type(exc).__name__, exc))
                events.emit("serve_weight_swap_failed",
                            step=self._served_step, phase="poll",
                            error="%s: %s" % (type(exc).__name__, exc))
                return None
            if not steps:
                return None
            latest = steps[-1]
            if (not force and self._served_step is not None
                    and latest <= self._served_step):
                return None
            previous = self._served_step
            try:
                self.reload(latest)
            except Exception as exc:
                # the PR-7 rule: a bad snapshot must not take the service
                # down — previous weights keep serving, the failure is a
                # counter and a log line, and the next poll retries
                self._c_failures.inc()
                info("hot swap to step %d REFUSED (still serving step %r): "
                     "%s: %s" % (latest, previous, type(exc).__name__, exc))
                events.emit("serve_weight_swap_failed", step=latest,
                            phase="reload", previous=previous,
                            error="%s: %s" % (type(exc).__name__, exc))
                return None
            self._served_step = latest
            self._c_swaps.inc()
        trace.instant("serve.weight_swap", cat="serve", step=int(latest),
                      previous=previous if previous is None else int(previous))
        events.emit("serve_weight_swap", step=latest, previous=previous,
                    forced=bool(force))
        info("hot swap: serving weights of step %d (was %r)"
             % (latest, previous))
        if self.summaries is not None:
            self.summaries.event(int(latest), "serve_weight_swap", {
                "step": int(latest),
                "previous": previous,
                "forced": bool(force),
            })
        return latest

    # ------------------------------------------------------------------ #
    # lifecycle

    def start(self):
        """Poll every ``interval_s`` seconds on a daemon thread."""
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="serve-weight-watcher"
            )
            thread = self._thread
        thread.start()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception as exc:  # belt and braces: the loop survives
                info("checkpoint watcher check failed: %s: %s"
                     % (type(exc).__name__, exc))

    def close(self):
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(5.0)
        for name in self._metric_names:
            self.registry.unregister(name)

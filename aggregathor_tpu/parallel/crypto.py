"""Payload encryption under the session secret (off the hot path).

The reference ships working TLS channels for its control plane
(tf_patches/patches/grpc_channel.patch:70-85, ``SECURE_GRPC=1``): gradient
and state bytes crossing its open network are encrypted in flight.  Under
single-controller SPMD the in-flight surface is the TPU interconnect (not
addressable by guest code) and the multi-host control plane, whose
runtime-internal channel exposes no TLS knob to guest code
(docs/transport.md "In-flight closure") — so this module encrypts the
BYTES the framework itself owns, wherever they travel: checkpoint
snapshots persisted to shared disk (``--encrypt-checkpoints``) and the
bring-up handshake payloads exchanged across hosts
(``auth.authenticate_processes``, context ``b"handshake-enc"``), each
under a key derived from the same session secret that authenticates them.

Construction (stdlib-only — the environment has no AEAD library, and the
box's pip is sealed):

- key      = SHA-256(secret || len("ckpt-enc") || "ckpt-enc" || 0)
             (``auth.derive_worker_key`` — its own context, so the
             encryption key family is disjoint from every tagging family)
- nonce    = 16 fresh ``os.urandom`` bytes per snapshot
- keystream = SHAKE-256(key || nonce || step), one ``digest(len(data))``
             call — the sponge as an XOF-keyed stream cipher (the cSHAKE/
             KMAC construction), C-speed for multi-MB states
- ciphertext = plaintext XOR keystream  (numpy, vectorized)
- blob     = MAGIC || nonce || ciphertext

Integrity is NOT this layer's job: ``obs.Checkpoints`` tags the blob with
the existing HMAC machinery (encrypt-then-MAC — verification rejects
tampered ciphertext before a single keystream byte is derived).  A
plaintext sentinel is still prepended before encryption so a cipher used
WITHOUT an authenticator fails loudly on a wrong secret instead of feeding
keystream garbage to the deserializer.
"""

import hashlib
import os
import struct

import numpy as np

from ..utils import UserException
from .auth import derive_worker_key

_MAGIC = b"ATPC1"  # versioned container tag: bump on format change
_SENTINEL = b"ATPP"  # plaintext marker: wrong-key decrypt cannot produce it
_NONCE_BYTES = 16


def _keystream(key, nonce, step, length):
    material = key + nonce + struct.pack("<q", int(step))
    return hashlib.shake_256(material).digest(length)


def _xor(data, stream):
    a = np.frombuffer(data, np.uint8)
    b = np.frombuffer(stream, np.uint8)
    return np.bitwise_xor(a, b).tobytes()


class SnapshotCipher:
    """Encrypts/decrypts byte blobs under a session-secret key.

    Step binding: the step number seasons the keystream, so two snapshots
    at different steps never share a keystream even under nonce reuse.

    ``context`` selects the key family (default: checkpoint encryption);
    the bring-up handshake passes ``b"handshake-enc"`` so control-plane
    ciphertext and checkpoint ciphertext never share keys."""

    def __init__(self, session_secret, context=b"ckpt-enc"):
        self.key = derive_worker_key(session_secret, 0, context=context)

    def encrypt(self, step, data):
        nonce = os.urandom(_NONCE_BYTES)
        plain = _SENTINEL + bytes(data)
        return _MAGIC + nonce + _xor(plain, _keystream(self.key, nonce, step, len(plain)))

    def decrypt(self, step, blob):
        blob = bytes(blob)
        if not blob.startswith(_MAGIC):
            raise UserException(
                "Snapshot is not encrypted (or predates encryption): missing "
                "the %r container tag. Restore it without --encrypt-checkpoints; "
                "the next save writes an encrypted snapshot" % (_MAGIC,)
            )
        nonce = blob[len(_MAGIC):len(_MAGIC) + _NONCE_BYTES]
        ct = blob[len(_MAGIC) + _NONCE_BYTES:]
        plain = _xor(ct, _keystream(self.key, nonce, step, len(ct)))
        if not plain.startswith(_SENTINEL):
            raise UserException(
                "Snapshot decryption failed: wrong --session-secret or a "
                "corrupted snapshot"
            )
        return plain[len(_SENTINEL):]

    @staticmethod
    def is_encrypted(blob):
        return bytes(blob[:len(_MAGIC)]) == _MAGIC

"""Byzantine gradient attacks.

The reference plumbs ``--attack/--attack-args/--nb-real-byz-workers`` through
the CLI but leaves the gradient-attack hook an acknowledged TODO
(runner.py:145-155, 345); its only in-repo adversary is the data-poisoning
``mnistAttack`` experiment.  This module implements the hook for real.

Threat model (SURVEY.md §7 hard part (e)): the first ``r`` global worker slots
are Byzantine.  Two attack families:

- **local** attacks read only the attacker's own gradient slot — honest
  modeling of an isolated malicious worker (applied inside the worker's
  shard_map scope, before any collective);
- **omniscient** attacks model the classic strongest adversary that sees all
  honest gradients and coordinates the coalition (Fall of Empires, A Little
  Is Enough).  These are applied to the gathered column block, where
  coordinate-wise honest statistics are available blockwise.

Both families are deterministic functions of (gradient(s), worker index, PRNG
key) so runs are reproducible.
"""

import math

import jax
import jax.numpy as jnp

from ..utils import ClassRegister, parse_keyval

attacks = ClassRegister("attack")


def register(name, cls):
    return attacks.register(name, cls)


def itemize():
    return attacks.itemize()


def instantiate(name, nb_workers, nb_byz_workers, args=None):
    return attacks.get(name)(nb_workers, nb_byz_workers, args or [])


class Attack:
    """Base attack. ``omniscient`` selects which hook the engine calls."""

    omniscient = False
    #: typed key:value argument defaults accepted by this attack — parsed
    #: STRICTLY (an unknown key raises instead of silently vanishing; same
    #: contract as the GARs), which is what lets the chaos DSL forward
    #: regime settings to attacks without swallowing typos
    ARG_DEFAULTS = {}

    def __init__(self, nb_workers, nb_byz_workers, args):
        self.nb_workers = int(nb_workers)
        self.nb_byz_workers = int(nb_byz_workers)
        self.args = parse_keyval(args, self.ARG_DEFAULTS, strict=True)

    def apply_local(self, grad, key):
        """Transform one Byzantine worker's own (d,) gradient."""
        raise NotImplementedError

    def apply_matrix(self, matrix, byz_mask, key):
        """Transform the (n, d_block) gathered block; rows where ``byz_mask``
        is True belong to the coalition (omniscient attacks only)."""
        raise NotImplementedError


class SignFlipAttack(Attack):
    """Submit -scale times the true gradient (classic reversed-gradient attacker)."""

    ARG_DEFAULTS = {"scale": 1.0}

    def __init__(self, nb_workers, nb_byz_workers, args):
        super().__init__(nb_workers, nb_byz_workers, args)
        self.scale = self.args["scale"]

    def apply_local(self, grad, key):
        return -self.scale * grad


class ZeroAttack(Attack):
    """Submit the zero vector (silent freeloader / stalling attacker)."""

    def apply_local(self, grad, key):
        return jnp.zeros_like(grad)


class GaussianAttack(Attack):
    """Submit pure Gaussian noise of tunable deviation."""

    ARG_DEFAULTS = {"deviation": 100.0}

    def __init__(self, nb_workers, nb_byz_workers, args):
        super().__init__(nb_workers, nb_byz_workers, args)
        self.deviation = self.args["deviation"]

    def apply_local(self, grad, key):
        return self.deviation * jax.random.normal(key, grad.shape, grad.dtype)


class InfAttack(Attack):
    """Submit non-finite values (what a crashed/lossy worker degenerates to;
    pairs with the NaN-absorbing GARs, average-nan.py parity)."""

    def apply_local(self, grad, key):
        return jnp.full_like(grad, jnp.nan)


class EmpireAttack(Attack):
    """'Fall of Empires' (Xie et al. 2019): the coalition submits
    -epsilon x mean(honest gradients), reversing the aggregate direction
    while staying inside the honest cloud for small epsilon."""

    omniscient = True
    ARG_DEFAULTS = {"epsilon": 1.1}

    def __init__(self, nb_workers, nb_byz_workers, args):
        super().__init__(nb_workers, nb_byz_workers, args)
        self.epsilon = self.args["epsilon"]

    def apply_matrix(self, matrix, byz_mask, key):
        honest = ~byz_mask
        count = jnp.maximum(jnp.sum(honest), 1)
        mean = jnp.sum(jnp.where(honest[:, None], matrix, 0.0), axis=0) / count
        forged = -self.epsilon * mean
        return jnp.where(byz_mask[:, None], forged[None, :], matrix)


class LittleAttack(Attack):
    """'A Little Is Enough' (Baruch et al. 2019): the coalition shifts the
    honest mean by z standard deviations per coordinate — small enough to
    evade distance-based detection, large enough to bias the aggregate.
    ``z`` defaults to the paper's quantile formula from (n, f)."""

    omniscient = True
    ARG_DEFAULTS = {"z": 0.0, "negative": True}

    def __init__(self, nb_workers, nb_byz_workers, args):
        super().__init__(nb_workers, nb_byz_workers, args)
        kv = self.args
        if kv["z"] > 0.0:
            self.z = kv["z"]
        else:
            n, f = self.nb_workers, self.nb_byz_workers
            s = n // 2 + 1 - f  # supporters needed for majority
            phi = max(min((n - f - s) / max(n - f, 1), 1.0 - 1e-6), 1e-6)
            self.z = math.sqrt(2.0) * _erfinv(2.0 * phi - 1.0)
        self.sign = -1.0 if kv["negative"] else 1.0

    def apply_matrix(self, matrix, byz_mask, key):
        honest = ~byz_mask
        count = jnp.maximum(jnp.sum(honest), 1)
        mean = jnp.sum(jnp.where(honest[:, None], matrix, 0.0), axis=0) / count
        var = jnp.sum(jnp.where(honest[:, None], (matrix - mean[None, :]) ** 2, 0.0), axis=0) / count
        forged = mean + self.sign * self.z * jnp.sqrt(var)
        return jnp.where(byz_mask[:, None], forged[None, :], matrix)


def _erfinv(x):
    return float(jax.scipy.special.erfinv(jnp.float64(x) if jax.config.jax_enable_x64 else jnp.float32(x)))


register("signflip", SignFlipAttack)
register("zero", ZeroAttack)
register("gaussian", GaussianAttack)
register("inf", InfAttack)
register("empire", EmpireAttack)
register("little", LittleAttack)

"""Device mesh construction.

The reference greedily allocates TF devices to worker/ps/eval roles across
tasks (cluster.py:147-221).  On TPU the device topology is static and the
allocation problem collapses to axis sizing: an ``n_workers``-wide ``worker``
axis (data parallelism across Byzantine workers) optionally times a ``model``
axis (tensor parallelism within each worker, for models that shard).

``jax.make_mesh`` lays axes out so that the fastest-varying axis rides ICI
neighbours; multi-host (DCN) meshes come from JAX's multi-process runtime
(`jax.distributed.initialize`) with the same axis names — nothing in the
engine changes between one chip and a multi-host pod.
"""

import jax

from .. import config

worker_axis = config.worker_axis
pipe_axis = config.pipe_axis
model_axis = config.model_axis


def make_mesh(nb_workers=None, model_parallelism=1, pipeline_parallelism=1, devices=None):
    """Build a Mesh with axes ``(worker, pipe, model)``.

    Args:
      nb_workers: size of the worker axis; defaults to all devices divided by
        ``model_parallelism * pipeline_parallelism``.
      model_parallelism: size of the tensor-parallel axis inside each stage
        (sequence and expert parallelism ride this axis too).
      pipeline_parallelism: number of pipeline stages inside each worker.
      devices: explicit device list (defaults to ``jax.devices()``).
    Returns:
      ``jax.sharding.Mesh`` with named axes (worker, pipe, model).
    """
    devices = list(devices if devices is not None else jax.devices())
    per_worker = model_parallelism * pipeline_parallelism
    if nb_workers is None:
        nb_workers = len(devices) // per_worker
    need = nb_workers * per_worker
    if need > len(devices):
        from ..utils import UserException

        raise UserException(
            "Mesh needs %d devices (%d workers x %d pipe x %d model) but only %d are available"
            % (need, nb_workers, pipeline_parallelism, model_parallelism, len(devices))
        )
    return jax.make_mesh(
        (nb_workers, pipeline_parallelism, model_parallelism),
        (worker_axis, pipe_axis, model_axis),
        devices=devices[:need],
    )


def factor_devices(n_devices):
    """Split ``n_devices`` into (workers, pipe, model) axis sizes.

    Used by the multi-chip dry run to always exercise every parallelism axis
    the device count allows: the odd part widens the worker axis, then the
    factors of two go round-robin to the axes that are still 1 — so even
    counts always light up at least a second axis. 8 -> (2, 2, 2),
    4 -> (2, 2, 1), 6 -> (3, 2, 1), 12 -> (3, 2, 2), 2 -> (2, 1, 1).
    """
    sizes = [1, 1, 1]
    remaining = int(n_devices)
    while remaining % 2 == 0:
        remaining //= 2
        sizes[0] *= 2
    odd, twos = remaining, sizes[0]
    sizes = [odd, 1, 1]
    slot = 1 if odd > 1 else 0
    while twos > 1:
        sizes[slot] *= 2
        twos //= 2
        slot = (slot + 1) % 3
    return tuple(sizes)

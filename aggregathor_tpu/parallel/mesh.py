"""Device mesh construction.

The reference greedily allocates TF devices to worker/ps/eval roles across
tasks (cluster.py:147-221).  On TPU the device topology is static and the
allocation problem collapses to axis sizing: an ``n_workers``-wide ``worker``
axis (data parallelism across Byzantine workers) optionally times a ``model``
axis (tensor parallelism within each worker, for models that shard).

``jax.make_mesh`` lays axes out so that the fastest-varying axis rides ICI
neighbours; multi-host (DCN) meshes come from JAX's multi-process runtime
(`jax.distributed.initialize`) with the same axis names — nothing in the
engine changes between one chip and a multi-host pod.
"""

import jax

from .. import config

worker_axis = config.worker_axis
model_axis = config.model_axis


def make_mesh(nb_workers=None, model_parallelism=1, devices=None):
    """Build a Mesh with axes ``(worker, model)``.

    Args:
      nb_workers: size of the worker axis; defaults to all devices divided by
        ``model_parallelism``.
      model_parallelism: size of the tensor-parallel axis inside each worker.
      devices: explicit device list (defaults to ``jax.devices()``).
    Returns:
      ``jax.sharding.Mesh`` with named axes (worker, model).
    """
    devices = list(devices if devices is not None else jax.devices())
    if nb_workers is None:
        nb_workers = len(devices) // model_parallelism
    need = nb_workers * model_parallelism
    if need > len(devices):
        from ..utils import UserException

        raise UserException(
            "Mesh needs %d devices (%d workers x %d model) but only %d are available"
            % (need, nb_workers, model_parallelism, len(devices))
        )
    return jax.make_mesh((nb_workers, model_parallelism), (worker_axis, model_axis), devices=devices[:need])

"""Wire codecs: compressed robust gradient exchange (docs/engine.md, "The
wire").

At production ``n`` and ``d`` the (n, d) submission stack IS the bandwidth
bill — the reference paid it in full-precision UDP datagrams, and the bf16
``exchange_dtype`` twin only halves it.  This module generalizes that
dtype-only twin into a pluggable **wire codec**: every worker's submission
is ENCODED at the sender (after the worker-local attacks — an attacker
forges what it transmits), crosses the simulated transport as the encoded
payload (a dropped packet drops ENCODED bytes), and is DECODED at the
aggregation boundary so every GAR sees float32 rows.  OptiReduce
(arXiv:2310.06993) motivates the lever: the cloud tail is bandwidth-bound,
so fewer bytes per row is steps/s, not just a smaller bill.

Codecs (``--exchange`` on the runner; ``parse_exchange_spec`` grammar):

- ``f32``/``float32`` — the uncompressed wire (no codec, no dtype cast).
- ``bf16``/``bfloat16`` — the historical dtype twin: normalizes onto the
  engine's ``exchange_dtype`` path (bit-compatible with existing runs,
  applied at the collective boundary), 2x.
- ``int8[:ef]`` — per-row symmetric quantization with a traced float32
  scale (``max|row| / 127``): ~3.97x at large d.  A row whose magnitude
  is non-finite cannot encode — its wire image is a NaN row, absorbed by
  the NaN-tolerant rules inside the same declared-f budget as a lossy row.
- ``topk:k=K[,ef]`` / ``topk:frac=F[,ef]`` — magnitude top-k
  sparsification (value + index per kept coordinate, ``d/(2k)``x); NaN
  coordinates sort as +inf magnitude so a poisoned coordinate still
  crosses the wire instead of silently vanishing.

``ef`` enables **error feedback** (Karimireddy et al., SignSGD/EF-style):
the worker transmits ``C(g + e)`` and carries the residual
``e' = (g + e) - C(g + e)`` so quantization error accumulates into later
submissions instead of being lost — the difference between biased
sparsification and a convergent one.  The per-worker residual rides
``TrainState.ef`` (worker-sharded, checkpointed — core/train_state.py), so
restore and guardian rollback preserve it bit-exactly.

Feasibility is validated at parse/construction time, not at step 1e6:
the fixed-point masked path (``--secure-mask``) refuses loudly (a lossy
wire would corrupt the exact mod-2^64 pad cancellation), the sharded
engine refuses (per-leaf EF state is a different protocol; bf16 stays
available there), and an infeasible ``topk`` budget refuses when ``d``
is known.  ``wire_roundtrip`` is THE one place owning the precision-loss
semantics of rows that cross the wire (forged rows are squeezed through
it exactly like honest ones — parallel/engine.py's three call sites).

Composition with bounded-wait v3's age reweighting (``--stale-reweight``):
a stale carry row is stored ENCODED (the wire payload the aggregator last
received), and the reweight coefficient c(a) = 1/(1+a) is applied by the
aggregate AFTER this module's decode — the quantization scale and the age
discount compose as two traced scalars on the decoded f32 row, so neither
the codec nor the EF residual ever sees a damped value (a stale worker's
residual is frozen by the arrived-mask write-back, engine.py).
"""

import numpy as np

from ..utils import UserException

#: wire bytes of one float32 coordinate / one float32 scalar
_F32_BYTES = 4
#: wire bytes of one int32 coordinate index (top-k payload)
_I32_BYTES = 4


def _parse_options(body):
    """``k=64,ef`` -> {"k": "64", "ef": True}; bare keys are flags."""
    options = {}
    for part in body.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            key, value = part.split("=", 1)
            options[key.strip()] = value.strip()
        else:
            options[part] = True
    return options


def parse_exchange_spec(spec):
    """``--exchange`` spec -> ``(exchange_dtype, codec)``.

    Exactly one of the pair is non-None (both None for the f32 wire):
    ``bf16`` normalizes onto the engine's historical dtype path so
    existing bf16 runs stay bit-identical; ``int8``/``topk`` return a
    :class:`WireCodec`.  Accepts an already-constructed codec and passes
    it through (the test/benchmark surface)."""
    if spec is None:
        return None, None
    if isinstance(spec, WireCodec):
        return None, spec
    if not isinstance(spec, str):
        raise UserException(
            "--exchange wants a spec string or a WireCodec (got %r)" % (spec,)
        )
    name, _, body = spec.partition(":")
    name = name.strip().lower()
    options = _parse_options(body)

    def reject_options(allowed=()):
        unknown = sorted(set(options) - set(allowed))
        if unknown:
            raise UserException(
                "--exchange %s does not take option(s) %s"
                % (name, ", ".join(unknown))
            )

    if name in ("f32", "float32"):
        reject_options()
        return None, None
    if name in ("bf16", "bfloat16"):
        reject_options()
        import jax.numpy as jnp

        return jnp.dtype(jnp.bfloat16), None
    def ef_flag():
        # ef is a bare flag: an explicit value like ef=0 reads as intent
        # to DISABLE, and silently enabling would change the TrainState
        # layout behind the operator's back — refuse anything but the flag
        ef = options.get("ef", False)
        if ef is not True and ef is not False:
            raise UserException(
                "--exchange %s: ef is a bare flag — write ':...,ef' to "
                "enable error feedback, omit it to disable (got ef=%s)"
                % (name, ef)
            )
        return ef

    if name == "int8":
        reject_options(("ef",))
        return None, Int8Codec(ef=ef_flag())
    if name == "topk":
        reject_options(("k", "frac", "ef"))
        k = options.get("k")
        frac = options.get("frac")
        if (k is None) == (frac is None):
            raise UserException(
                "--exchange topk wants exactly one of k=K or frac=F "
                "(e.g. topk:k=4096,ef or topk:frac=0.0625,ef)"
            )
        try:
            k = None if k is None else int(k)
            frac = None if frac is None else float(frac)
        except ValueError:
            raise UserException("--exchange topk: k wants an int, frac a float")
        return None, TopKCodec(k=k, frac=frac, ef=ef_flag())
    raise UserException(
        "unknown --exchange spec %r (know: f32, bf16, int8[:ef], "
        "topk:k=K[,ef], topk:frac=F[,ef])" % (spec,)
    )


class WireCodec:
    """One wire codec: ``encode`` at the sender, ``decode`` at the
    aggregation boundary, ``roundtrip`` where the engine only needs the
    wire IMAGE (the fused step simulates the transport in-graph).

    All row methods take/return the LAST-axis-``d`` single row the
    submission pipeline works in; ``*_rows`` vmap over a leading worker
    axis.  ``payload`` is a pytree of arrays — what actually crosses the
    host boundary on the bounded-wait path."""

    name = "wire"
    uses_ef = False

    # -- contract ------------------------------------------------------ #

    def encode(self, row):
        raise NotImplementedError

    def decode(self, payload, d):
        raise NotImplementedError

    def bytes_per_row(self, d):
        """Wire bytes of one encoded (d,) row (payload + side channel)."""
        raise NotImplementedError

    def payload_zeros(self, d):
        """Host-side (numpy) zeroed payload for a slot nobody submitted —
        content is irrelevant (the aggregate masks missing slots to NaN
        AFTER decoding), only the pytree structure/shapes matter."""
        raise NotImplementedError

    def validate_d(self, d):
        """Refuse an infeasible codec budget once ``d`` is known."""

    # -- shared machinery ---------------------------------------------- #

    def roundtrip(self, row):
        """The wire image of one row: encode then decode, fused in-graph."""
        return self.decode(self.encode(row), row.shape[-1])

    def roundtrip_rows(self, rows):
        import jax

        return jax.vmap(self.roundtrip)(rows)

    def decode_rows(self, payload, d):
        import jax

        return jax.vmap(lambda p: self.decode(p, d))(payload)

    def ef_roundtrip(self, row, ef_row):
        """Error-feedback transmit: returns ``(wire_image, new_ef)`` where
        the image is ``C(row + ef)`` and ``new_ef`` the residual the
        worker carries into its next submission.  A non-finite wire image
        resets the residual (a NaN row must not poison every later send)."""
        _, decoded, new_ef = self.ef_encode(row, ef_row)
        return decoded, new_ef

    def ef_encode(self, row, ef_row):
        """``(payload, wire_image, new_ef)`` — the bounded-wait submission
        form (the payload crosses the host boundary, the image feeds the
        digest, the residual is written back on arrival)."""
        import jax.numpy as jnp

        target = row.astype(jnp.float32) + ef_row
        payload = self.encode(target)
        decoded = self.decode(payload, row.shape[-1])
        new_ef = jnp.where(jnp.isfinite(decoded), target - decoded,
                           jnp.zeros_like(target))
        return payload, decoded, new_ef

    def ratio(self, d):
        """Nominal compression ratio vs the f32 wire."""
        return (d * _F32_BYTES) / float(self.bytes_per_row(d))

    def validate_for(self, gar=None):
        """Construction-time feasibility (re-run on every guardian
        escalation rebuild — the engine constructs through here)."""
        if gar is not None and getattr(gar, "masking", None) is not None:
            raise UserException(
                "--secure-mask's fixed-point pairwise pads cancel exactly "
                "mod 2^64 over the EXACT float32 rows; a lossy wire codec "
                "(%s) would corrupt the cancellation into one-time-pad "
                "garbage — run masking on the f32/bf16 wire" % self.spec()
            )

    def spec(self):
        return self.name


class Int8Codec(WireCodec):
    """Per-row symmetric int8 quantization with a traced float32 scale.

    ``scale = max|row| / 127``; coordinates quantize to round(row/scale)
    in [-127, 127].  The scale rides the payload (4 bytes/row — the
    "traced scales": a per-step data value, never a compiled constant, so
    steady state never recompiles).  A row whose magnitude is non-finite
    cannot encode — int8 has no inf — and its wire image is a NaN row,
    which the NaN-tolerant rules absorb within the declared-f budget."""

    name = "int8"

    def __init__(self, ef=False):
        self.uses_ef = bool(ef)

    def encode(self, row):
        import jax.numpy as jnp

        row = row.astype(jnp.float32)
        scale = jnp.max(jnp.abs(row), axis=-1) / jnp.float32(127.0)
        safe = jnp.where((scale > 0) & jnp.isfinite(scale), scale, 1.0)
        q = jnp.clip(jnp.round(row / safe[..., None]), -127.0, 127.0)
        # a NaN coordinate would cast to an arbitrary int8: pin it to 0
        # (the whole row reads NaN at decode anyway — the scale is NaN)
        q = jnp.where(jnp.isfinite(q), q, 0.0).astype(jnp.int8)
        return {"q": q, "scale": scale}

    def decode(self, payload, d):
        import jax.numpy as jnp

        scale = payload["scale"]
        out = payload["q"].astype(jnp.float32) * scale[..., None]
        return jnp.where(jnp.isfinite(scale)[..., None], out, jnp.nan)

    def bytes_per_row(self, d):
        return d + _F32_BYTES  # 1 byte/coordinate + the f32 scale

    def payload_zeros(self, d):
        return {"q": np.zeros((d,), np.int8),
                "scale": np.zeros((), np.float32)}

    def spec(self):
        return "int8:ef" if self.uses_ef else "int8"


class TopKCodec(WireCodec):
    """Magnitude top-k sparsification: the k largest-|value| coordinates
    cross the wire as (float32 value, int32 index) pairs; everything else
    decodes to zero.  ``frac`` resolves to ``k = max(1, round(frac * d))``
    once ``d`` is known (static per engine — no recompiles).  NaN
    coordinates sort as +inf magnitude, so a poisoned coordinate is
    transmitted (and lands in the GAR's NaN accounting) instead of being
    silently zeroed by its own corruption.  Biased without error
    feedback — pass ``ef`` for training runs (docs/engine.md)."""

    name = "topk"

    def __init__(self, k=None, frac=None, ef=False):
        if k is not None and k < 1:
            raise UserException("--exchange topk wants k >= 1 (got %d)" % k)
        if frac is not None and not 0.0 < frac <= 1.0:
            raise UserException(
                "--exchange topk wants frac in (0, 1] (got %g)" % frac
            )
        self.k = None if k is None else int(k)
        self.frac = None if frac is None else float(frac)
        self.uses_ef = bool(ef)

    def _k_for(self, d):
        k = self.k if self.k is not None else max(1, int(round(self.frac * d)))
        if k > d:
            raise UserException(
                "--exchange topk: k=%d exceeds the model dimension d=%d "
                "(a sparsifier that keeps more than everything is a "
                "misconfiguration, not a wire)" % (k, d)
            )
        if k > d // 2:
            # 8 bytes per kept coordinate (f32 value + int32 index): past
            # d/2 the "compressed" payload EXCEEDS the raw f32 wire and
            # the compression_ratio gauge's >= 1 contract breaks — refuse
            # the inflation instead of shipping it silently
            raise UserException(
                "--exchange topk: k=%d > d/2 = %d INFLATES the wire (each "
                "kept coordinate ships value + index, 8 bytes vs 4 raw) — "
                "use k <= d/2, or the f32/bf16 wire if you want everything"
                % (k, d // 2)
            )
        return k

    def validate_d(self, d):
        self._k_for(d)

    def encode(self, row):
        import jax
        import jax.numpy as jnp

        row = row.astype(jnp.float32)
        k = self._k_for(row.shape[-1])
        mag = jnp.where(jnp.isnan(row), jnp.inf, jnp.abs(row))
        _, idx = jax.lax.top_k(mag, k)
        return {"v": jnp.take(row, idx), "i": idx.astype(jnp.int32)}

    def decode(self, payload, d):
        import jax.numpy as jnp

        return jnp.zeros((d,), jnp.float32).at[payload["i"]].set(payload["v"])

    def bytes_per_row(self, d):
        return self._k_for(d) * (_F32_BYTES + _I32_BYTES)

    def payload_zeros(self, d):
        k = self._k_for(d)
        return {"v": np.zeros((k,), np.float32), "i": np.zeros((k,), np.int32)}

    def spec(self):
        body = "k=%d" % self.k if self.k is not None else "frac=%g" % self.frac
        return "topk:%s%s" % (body, ",ef" if self.uses_ef else "")


def wire_roundtrip(rows, dtype=None, codec=None):
    """THE precision-loss semantics of rows crossing the wire, in one
    place: forged rows are squeezed through the exchange exactly like
    honest ones (an omniscient attacker's matrix still ships as encoded
    bytes).  ``dtype`` is the engine's ``exchange_dtype`` twin, ``codec``
    the generalized wire; both None is the f32 wire (identity)."""
    import jax.numpy as jnp

    if codec is not None:
        return codec.roundtrip_rows(rows) if rows.ndim > 1 else codec.roundtrip(rows)
    if dtype is not None:
        return rows.astype(dtype).astype(jnp.float32)
    return rows


def bytes_per_row(d, dtype=None, codec=None):
    """Wire bytes of one (d,) submission row under the configured
    exchange — the accounting behind ``bytes_on_wire_total``."""
    if codec is not None:
        return int(codec.bytes_per_row(d))
    if dtype is not None:
        return int(d) * int(np.dtype(dtype).itemsize)
    return int(d) * _F32_BYTES


def compression_ratio(d, dtype=None, codec=None):
    """Bytes-on-wire ratio vs the f32 exchange (>= 1)."""
    return (int(d) * _F32_BYTES) / float(bytes_per_row(d, dtype=dtype, codec=codec))


def describe(dtype=None, codec=None):
    """The exchange spec string for telemetry/summary labels."""
    if codec is not None:
        return codec.spec()
    if dtype is not None:
        return str(np.dtype(dtype).name)
    return "float32"

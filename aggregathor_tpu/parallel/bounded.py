"""Bounded-wait robust aggregation: never wait on the slowest worker.

The fused SPMD step (``engine.py``) is synchronous by construction — one
compiled program, one dispatch, the step takes as long as the slowest
worker's gradient.  That is exactly the failure mode AggregaThor's robust
GARs make unnecessary: a rule sized for ``f`` Byzantine rows absorbs a
missing row for free (a lost UDP packet becomes a NaN row, SURVEY L1), so
the aggregator may close the round on a DEADLINE instead of on the last
submission (OptiReduce's tail-optimal allreduce, arXiv:2310.06993;
"Efficient AllReduce with Stragglers", arXiv:2505.23523).

:class:`BoundedWaitStep` is that protocol, host-orchestrated over the
unified engine's two bounded-wait executables:

1. ``engine.build_worker_grad``: ONE jitted per-worker submission
   executable, dispatched n times per step on its own submission thread —
   per-worker async device streams; each thread's dispatch returns
   immediately and the submission "arrives" when its row materializes.
2. The host polls arrivals against ``deadline`` seconds
   (``concurrent.futures.wait``).  Workers that miss it are marked timed
   out; their slot in the (n, d) submission buffer is garbage the
   aggregator masks to NaN IN GRAPH — the same row the chaos straggler
   simulation produced, now produced by the real clock.
3. ``engine.build_bounded_aggregate``: one jitted aggregate+update
   executable (omniscient attacks, quarantine, GAR, optax, probe, flight —
   the fused step's shared code paths) consuming the submission buffer and
   the arrival mask.

**f-accounting** (docs/engine.md): timeout rows spend the same declared-f
budget as attack rows.  With ``t`` timeouts and ``b`` Byzantine rows the
rule's guarantee holds iff ``t + b <= f`` — size ``f`` for BOTH tails.
A worker whose previous submission is still in flight when a new round
opens is skipped for that round (an immediate timeout): the per-worker
stream never queues more than one outstanding submission, which is what
bounds memory AND models a genuinely slow worker missing consecutive
rounds.

Straggler injection (:class:`HostStragglerModel`) maps a chaos schedule's
straggler regimes — or an explicit rate — to real wall-clock submission
delays, which is how the chaos/ simulation becomes the thing the protocol
is measured against (benchmarks/straggler_sweep.py).
"""

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

import jax
import numpy as np

from ..obs import trace
from ..utils import UserException


class HostStragglerModel:
    """Per-(step, worker) wall-clock submission delays.

    Deterministic in (seed, step, worker) like every chaos stream: a worker
    is late with the regime's ``straggler_rate`` (from ``chaos`` — a
    schedule whose ONLY adversity is straggler regimes — or the flat
    ``rate``), and a late worker sleeps ``stall_seconds`` before
    dispatching.  ``nb_eligible`` restricts lateness to the first K global
    workers (the schedule's ``straggle-workers`` knob / the --UDP first-k
    convention)."""

    def __init__(self, nb_workers, stall_seconds, rate=0.0, chaos=None,
                 nb_eligible=0, seed=0):
        self.nb_workers = int(nb_workers)
        self.stall_seconds = float(stall_seconds)
        self.rate = float(rate)
        self.chaos = chaos
        self.nb_eligible = int(nb_eligible)
        self.seed = int(seed)
        if chaos is not None:
            if chaos.has_attacks or chaos.has_drop or chaos.has_forgery:
                raise UserException(
                    "bounded-wait consumes ONLY straggler regimes from the "
                    "schedule (attack/drop/forge/tamper still need the "
                    "in-graph simulation of the synchronous step)"
                )
            if not chaos.has_stragglers:
                raise UserException(
                    "the schedule has no straggler regime; drop --chaos or "
                    "add one (e.g. '0:straggle=0.3')"
                )
            self.nb_eligible = chaos.stragglers.nb_eligible
        if self.stall_seconds < 0.0:
            raise UserException("straggler stall must be >= 0 seconds")
        if not 0.0 <= self.rate <= 1.0:
            raise UserException("straggler rate must lie in [0, 1]")
        if self.stall_seconds == 0.0 and (self.rate > 0.0 or chaos is not None):
            # a schedule/rate without a stall would silently inject nothing
            # — the one misconfiguration on this path that wouldn't be loud
            raise UserException(
                "a straggler rate/schedule needs --straggler-stall > 0 "
                "seconds to actually delay anyone"
            )

    def _rate_at(self, step):
        if self.chaos is not None:
            return float(self.chaos._straggler_rates[self.chaos.regime_at(step)])
        return self.rate

    def delay(self, step, worker):
        """Seconds worker ``worker`` holds its step-``step`` submission."""
        rate = self._rate_at(step)
        if rate <= 0.0 or self.stall_seconds <= 0.0:
            return 0.0
        if self.nb_eligible and worker >= self.nb_eligible:
            return 0.0
        # counter-based draw: reproducible and order-independent across the
        # submission threads (one Generator shared by n threads would be
        # neither)
        u = np.random.default_rng(
            (self.seed, int(step), int(worker))
        ).random()
        return self.stall_seconds if u < rate else 0.0


class BoundedWaitStep:
    """Host-orchestrated bounded-wait training step over a flat engine.

    ``step(state, batch) -> (state, metrics)`` — the same contract as the
    fused ``engine.build_step`` product, so the runner's train loop,
    divergence lag, forensics feed and guardian plumbing consume it
    unchanged.  ``deadline=None`` degrades to the synchronous protocol
    (wait for every submission) — the baseline the straggler sweep
    measures against.
    """

    def __init__(self, engine, loss_fn, tx, params_template, deadline=None,
                 straggler_model=None, registry=None):
        if deadline is not None and deadline <= 0.0:
            raise UserException("--step-deadline must be > 0 seconds")
        self.engine = engine
        self.nb_workers = engine.nb_workers
        self.deadline = deadline
        self.model = straggler_model
        self.grad_fn = engine.build_worker_grad(loss_fn)
        self.agg_fn = engine.build_bounded_aggregate(tx, params_template)
        self.pool = ThreadPoolExecutor(
            max_workers=self.nb_workers, thread_name_prefix="bw-submit"
        )
        # one outstanding submission per worker: a worker still in flight
        # when a new round opens is skipped (= an immediate timeout)
        self._in_flight = [None] * self.nb_workers
        self._round = 0
        self._round_lock = threading.Lock()
        # the deadline engages from the SECOND round: the first dispatch
        # compiles both executables, and charging the compile against the
        # deadline would time out every worker of step 0 (the perf report
        # excludes the compile step for the same reason)
        self._warm = False
        # one committed NaN row + zero loss reused for every missing slot
        d = sum(
            int(np.prod(np.shape(leaf)))
            for leaf in jax.tree_util.tree_leaves(params_template)
        )
        row_dtype = np.dtype(engine.exchange_dtype or np.float32)
        self._nan_template = (
            np.zeros((), np.float32), np.full((d,), np.nan, row_dtype),
        )
        self.timeouts_total = np.zeros((self.nb_workers,), np.int64)
        self._c_timeouts = self._c_rounds = self._g_deadline = None
        self._c_late = None
        if registry is not None:
            self._c_timeouts = registry.counter(
                "straggler_timeouts_total",
                "Worker submissions that missed the step deadline",
                labelnames=("worker",),
            )
            self._c_late = registry.counter(
                "straggler_skipped_rounds_total",
                "Rounds skipped because the worker's previous submission "
                "was still in flight",
                labelnames=("worker",),
            )
            self._c_rounds = registry.counter(
                "bounded_wait_rounds_total", "Bounded-wait aggregation rounds"
            )
            self._g_deadline = registry.gauge(
                "bounded_wait_deadline_seconds", "Configured step deadline"
            )
            if deadline is not None:
                self._g_deadline.set(float(deadline))

    # ------------------------------------------------------------------ #

    def _submit_one(self, round_id, step_idx, worker, params, rng, worker_batch):
        """Submission-thread body: injected stall, then dispatch + drain.
        Returns (worker, loss, row) or None when the round already closed
        (the dispatch would read donated buffers)."""
        if self.model is not None:
            stall = self.model.delay(step_idx, worker)
            if stall:
                time.sleep(stall)
        with self._round_lock:
            if round_id != self._round:
                return None  # round closed while we stalled: don't dispatch
            out = self.grad_fn(params, worker_batch, rng, step_idx, worker)
        try:
            loss, row = jax.block_until_ready(out)
        except Exception:
            return None  # buffers reclaimed under a concurrently-closed round
        return worker, loss, row

    def __call__(self, state, batch):
        n = self.nb_workers
        # the previous dispatch materialized the step counter; this read is
        # a host copy, not a device sync
        step_idx = int(jax.device_get(state.step))
        params, rng = state.params, state.rng
        futures, skipped = {}, []
        for w in range(n):
            prev = self._in_flight[w]
            if prev is not None and not prev.done():
                # still submitting a previous round: this worker misses the
                # current one outright (bounded queue, see module docstring)
                skipped.append(w)
                continue
            self._in_flight[w] = self.pool.submit(
                self._submit_one, self._round, step_idx, w, params, rng,
                jax.tree_util.tree_map(lambda x, _w=w: x[_w], batch),
            )
            futures[w] = self._in_flight[w]
        deadline = self.deadline if self._warm else None
        self._warm = True
        with trace.span("bounded_wait.collect", cat="train"):
            pending = set(futures.values())
            if deadline is None:
                if pending:
                    wait(pending)
            else:
                deadline_at = time.monotonic() + deadline
                while pending:
                    remaining = deadline_at - time.monotonic()
                    if remaining <= 0:
                        break
                    done, pending = wait(
                        pending, timeout=remaining, return_when=FIRST_COMPLETED
                    )
        # close the round: submissions that wake up from now on must not
        # dispatch against buffers the aggregate below will donate
        with self._round_lock:
            self._round += 1
        arrived = np.zeros((n,), bool)
        losses, rows = [], []
        for w in range(n):
            fut = futures.get(w)
            result = fut.result() if (fut is not None and fut.done()) else None
            if result is not None:
                arrived[w] = True
                losses.append(result[1])
                rows.append(result[2])
            else:
                losses.append(self._nan_template[0])
                rows.append(self._nan_template[1])
        self.timeouts_total += ~arrived
        if self._c_timeouts is not None:
            for w in np.nonzero(~arrived)[0]:
                self._c_timeouts.labels(worker=str(int(w))).inc()
            for w in skipped:
                self._c_late.labels(worker=str(int(w))).inc()
            self._c_rounds.inc()
        import jax.numpy as jnp

        return self.agg_fn(
            state, jnp.stack(rows), jnp.stack(losses),
            jnp.asarray(arrived),
        )

    def _cache_size(self):
        """Compile-count surface for the zero-recompile assertions AND the
        runner's CompileWatch: the MAX over the two bounded-wait
        executables, so steady state reads 1 like every fused step (a sum
        would read 2 and trip the watch's cache_size > 1 retrace alarm on
        the expected first compile)."""
        return max(self.grad_fn._cache_size(), self.agg_fn._cache_size())

    def close(self):
        self.pool.shutdown(wait=False, cancel_futures=True)

"""Bounded-wait robust aggregation: never wait on the slowest worker.

The fused SPMD step (``engine.py``) is synchronous by construction — one
compiled program, one dispatch, the step takes as long as the slowest
worker's gradient.  That is exactly the failure mode AggregaThor's robust
GARs make unnecessary: a rule sized for ``f`` Byzantine rows absorbs a
missing row for free (a lost UDP packet becomes a NaN row, SURVEY L1), so
the aggregator may close the round on a DEADLINE instead of on the last
submission (OptiReduce's tail-optimal allreduce, arXiv:2310.06993;
"Efficient AllReduce with Stragglers", arXiv:2505.23523).

:class:`BoundedWaitStep` is that protocol, host-orchestrated over the
unified engine's bounded-wait executables:

1. ``engine.build_worker_grad`` (flat) / ``engine.build_group_grad``
   (sharded, trivial in-group mesh) / ``engine.build_submesh_grad``
   (sharded, NONTRIVIAL (pipe x model) submeshes — bounded-wait v3):
   ONE jitted submission executable, dispatched once per SUBMISSION UNIT
   per step on its own thread — a unit is one worker in the flat mode,
   one worker-axis submesh (its k = n/W vmapped logical workers) in the
   sharded modes.  On a nontrivial submesh the unit's pipe/model
   collectives are INTERNAL to its program, so the W submissions stay
   independent and each carries its own deadline: a submesh that misses
   the window forfeits its k rows as a unit (``submesh_timeout`` on the
   journal).  Per-unit async device streams; each thread's dispatch
   returns immediately and the submission "arrives" when its rows
   materialize.
2. The host polls arrivals against a window — a fixed ``deadline``, or
   the :class:`~.deadline.DeadlineController`'s adaptive one (percentile
   of the observed arrival distribution, EMA-smoothed, floor/ceiling
   clamped).  Units that miss it are timed out as a whole (per-GROUP
   deadlines: a submesh that misses the window forfeits all k of its
   rows).
3. A timed-out worker's slot becomes either a **NaN row** (the v1
   protocol: absorbed like a fully-lossy link) or, under
   ``stale_infill``, its **CLEVER carry row** — the last submission the
   aggregator actually received from that worker, re-entered as a stale
   gradient, with ``stale_max_age`` bounding how many rounds a carry may
   be reused before it degrades back to a NaN row.
4. ``engine.build_bounded_aggregate``: one jitted aggregate+update
   executable (omniscient attacks, quarantine, GAR, optax, probe,
   flight, worker momentum write-back, secure digest lanes — the fused
   step's shared code paths) consuming the submission buffer and the
   arrival/stale masks.

**f-accounting** (docs/engine.md): timeout rows AND stale-infilled rows
spend the same declared-f budget as attack rows.  With ``t`` NaN
timeouts, ``s`` stale infills and ``b`` Byzantine rows the rule's
guarantee holds iff ``t + s + b <= f`` — a stale row is NOT free: its
worker may be Byzantine, and a Byzantine worker that straggles
deliberately re-enters its carried ATTACK row through the infill (the
laundering scenario the accounting exists for; the straggler sweep's
breakdown probe drives it for real).  A worker whose previous submission
is still in flight when a new round opens is skipped for that round (an
immediate timeout): the per-unit stream never queues more than one
outstanding submission, which is what bounds memory AND models a
genuinely slow worker missing consecutive rounds.

Straggler injection (:class:`HostStragglerModel`) maps a chaos schedule's
straggler regimes — or an explicit rate, optionally with a lognormal
heavy-tail ``jitter`` around the stall — to real wall-clock submission
delays, which is how the chaos/ simulation becomes the thing the protocol
is measured against (benchmarks/straggler_sweep.py).
"""

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

import jax
import numpy as np

from ..obs import events, trace
from ..utils import UserException


def _is_donation_race(exc):
    """The ONLY benign late failure: ``block_until_ready`` on outputs whose
    input buffers the closed round's aggregate donated out from under the
    dispatch (XLA surfaces it as a deleted/donated-buffer runtime error).
    Anything else a late submission raises — a device fault, an internal
    XLA error, a bug in the loss — is a real worker failure and must not
    be filed under the race."""
    text = str(exc).lower()
    return "delet" in text or "donat" in text


class HostStragglerModel:
    """Per-(step, worker) wall-clock submission delays.

    Deterministic in (seed, step, worker) like every chaos stream: a worker
    is late with the regime's ``straggler_rate`` (from ``chaos`` — a
    schedule whose ONLY adversity is straggler regimes — or the flat
    ``rate``), and a late worker sleeps ``stall_seconds`` before
    dispatching.  ``jitter`` (the regime's, or the flat argument) makes the
    stall heavy-tailed: a late worker sleeps ``stall * exp(jitter * N(0,1))``
    — lognormal with median ``stall`` — the realistic arrival distribution
    the deadline controller is exercised on.  ``nb_eligible`` restricts
    lateness to the first K global workers (the schedule's
    ``straggle-workers`` knob / the --UDP first-k convention)."""

    def __init__(self, nb_workers, stall_seconds, rate=0.0, chaos=None,
                 nb_eligible=0, seed=0, jitter=0.0):
        self.nb_workers = int(nb_workers)
        self.stall_seconds = float(stall_seconds)
        self.rate = float(rate)
        self.jitter = float(jitter)
        self.chaos = chaos
        self.nb_eligible = int(nb_eligible)
        self.seed = int(seed)
        if chaos is not None:
            if chaos.has_attacks or chaos.has_drop or chaos.has_forgery:
                raise UserException(
                    "bounded-wait consumes ONLY straggler regimes from the "
                    "schedule (attack/drop/forge/tamper still need the "
                    "in-graph simulation of the synchronous step)"
                )
            if not chaos.has_stragglers:
                raise UserException(
                    "the schedule has no straggler regime; drop --chaos or "
                    "add one (e.g. '0:straggle=0.3')"
                )
            self.nb_eligible = chaos.stragglers.nb_eligible
        if self.stall_seconds < 0.0:
            raise UserException("straggler stall must be >= 0 seconds")
        if not 0.0 <= self.rate <= 1.0:
            raise UserException("straggler rate must lie in [0, 1]")
        if self.jitter < 0.0:
            raise UserException(
                "straggler jitter must be >= 0 (the lognormal sigma around "
                "the stall), got %g" % self.jitter
            )
        if self.stall_seconds == 0.0 and (self.rate > 0.0 or chaos is not None):
            # a schedule/rate without a stall would silently inject nothing
            # — the one misconfiguration on this path that wouldn't be loud
            raise UserException(
                "a straggler rate/schedule needs --straggler-stall > 0 "
                "seconds to actually delay anyone"
            )

    def _rate_at(self, step):
        if self.chaos is not None:
            return float(self.chaos._straggler_rates[self.chaos.regime_at(step)])
        return self.rate

    def _jitter_at(self, step):
        if self.chaos is not None:
            return float(self.chaos._straggler_jitter[self.chaos.regime_at(step)])
        return self.jitter

    def delay(self, step, worker):
        """Seconds worker ``worker`` holds its step-``step`` submission."""
        rate = self._rate_at(step)
        if rate <= 0.0 or self.stall_seconds <= 0.0:
            return 0.0
        if self.nb_eligible and worker >= self.nb_eligible:
            return 0.0
        # counter-based draw: reproducible and order-independent across the
        # submission threads (one Generator shared by n threads would be
        # neither)
        gen = np.random.default_rng((self.seed, int(step), int(worker)))
        if gen.random() >= rate:
            return 0.0
        sigma = self._jitter_at(step)
        if sigma > 0.0:
            # lognormal around the stall: median == stall, heavy right tail
            return float(self.stall_seconds * np.exp(sigma * gen.standard_normal()))
        return self.stall_seconds


class BoundedWaitStep:
    """Host-orchestrated bounded-wait training step over the unified engine.

    ``step(state, batch) -> (state, metrics)`` — the same contract as the
    fused ``engine.build_step`` product, so the runner's train loop,
    divergence lag, forensics feed and guardian plumbing consume it
    unchanged.  ``deadline=None`` without a controller degrades to the
    synchronous protocol (wait for every submission) — the baseline the
    straggler sweep measures against.

    Args beyond the v1 surface:

    - ``controller``: a :class:`~.deadline.DeadlineController`; when set it
      supplies every warm round's window (the fixed ``deadline`` then only
      seeds/ceils it) and is fed the round's per-worker arrival vector.
    - ``stale_infill`` / ``stale_max_age``: a timed-out worker re-enters
      its CLEVER carry row (the last row this aggregator received from it)
      instead of a NaN row, for at most ``stale_max_age`` consecutive
      rounds — after that (or before any row ever arrived) it degrades
      back to the NaN drop.  Stale rows spend the declared-f budget
      exactly like timeouts (module docstring).
    - ``stale_reweight``: the v3 age-reweighted stale correction — a
      stale carry row of age a enters the aggregate scaled by
      c(a) = 1/(1 + a) (the unbiased-estimator framing of
      arXiv:2505.23523) instead of at full weight.  Requires
      ``stale_infill``; a worker whose rows go stale has its EF residual
      frozen (the arrived-mask write-back, unchanged) AND its re-entry
      discounted, and the damped row still SPENDS the f budget — the
      laundering accounting is not relaxed (a carried attack row damped
      is not a carried attack row dropped).  Each reweighted re-entry is
      a ``stale_reweight`` journal event carrying (worker, age,
      coefficient).
    - ``incremental``: fold each submission's DECODED row into an
      aggregate-side device buffer **the instant it lands**
      (``engine.build_incremental_fold``) instead of stacking everything
      at the round barrier — decode/transfer work overlaps the
      submissions still outstanding, which is where a compressed wire's
      decode cost goes to die.  The barrier-side aggregate then consumes
      the already-decoded buffer (``rows_form="decoded"``); numerics are
      identical to the stacked path (same decoder, same rows).  A fold
      issued while at least one submission is still pending counts as
      OVERLAPPED — ``exchange_overlap_fraction`` on the registry is the
      measured fraction (the win is a number, not a claim).  Flat
      submission units only (a per-submesh fold is a different layout).
    """

    def __init__(self, engine, loss_fn, tx, params_template, deadline=None,
                 straggler_model=None, registry=None, controller=None,
                 stale_infill=False, stale_max_age=4, stale_reweight=False,
                 incremental=False, topology=None):
        if deadline is not None and deadline <= 0.0:
            raise UserException("--step-deadline must be > 0 seconds")
        if stale_infill and deadline is None and controller is None:
            raise UserException(
                "--stale-infill needs a deadline (or the adaptive "
                "controller): the synchronous protocol never times anyone "
                "out, so there is nothing to infill"
            )
        if stale_reweight and not stale_infill:
            raise UserException(
                "--stale-reweight rescales STALE CARRY rows; without "
                "--stale-infill every miss is a NaN drop and there is "
                "nothing to reweight"
            )
        self.stale_max_age = int(stale_max_age)
        if stale_infill and self.stale_max_age < 1:
            raise UserException(
                "--stale-max-age must be >= 1 round (got %d)" % self.stale_max_age
            )
        self.engine = engine
        self.nb_workers = engine.nb_workers
        self.deadline = deadline
        self.controller = controller
        self.stale_infill = bool(stale_infill)
        self.stale_reweight = bool(stale_reweight)
        self.model = straggler_model
        self.momentum = engine.worker_momentum is not None
        self.secure = bool(engine.secure)
        self.codec = engine.codec
        self.ef = bool(engine.carries_ef)
        self.incremental = bool(incremental)
        # Submission units (module docstring): the flat mode dispatches one
        # executable per WORKER; the sharded mode one per worker-axis
        # SUBMESH (its k logical workers vmapped inside — per-group
        # deadlines: the group arrives, and times out, as a whole).
        self.grouped = bool(engine.sharded)
        if self.incremental and self.grouped:
            raise UserException(
                "--incremental-aggregation folds per-WORKER rows; the "
                "sharded mode's per-submesh submissions need a per-group "
                "fold layout, a different protocol — run the flat engine"
            )
        # the aggregation-tree host plane (topology/tree.py): drives the
        # per-level protocol once per round at the barrier, over the
        # stacked leaf rows — flat submissions only (the sharded mode's
        # per-submesh units are a different grouping than the tree's),
        # and not composable with the incremental fold (the tree needs
        # the stacked WIRE rows; the fold buffer is already decoded and
        # consumed)
        self.topology = topology
        if topology is not None and self.grouped:
            raise UserException(
                "--topology drives per-WORKER leaf rows; the sharded "
                "engine's per-submesh submission units are a different "
                "grouping than the tree's — run the flat engine"
            )
        if topology is not None and self.incremental:
            raise UserException(
                "--topology and --incremental-aggregation are mutually "
                "exclusive: the tree's custody plane signs the stacked "
                "wire rows at the barrier, which the incremental fold "
                "never materializes"
            )
        if self.grouped:
            from .mesh import model_axis, pipe_axis

            self.group_size = engine.workers_per_device
            self.nb_units = engine.nb_devices
            in_group = (engine.mesh.shape[pipe_axis]
                        * engine.mesh.shape[model_axis])
            if in_group != 1:
                # bounded-wait v3: a nontrivial (pipe x model) submesh is
                # one collective program per worker-axis group — W
                # independent submissions, each with its own deadline
                self.grad_fn = engine.build_submesh_grad(loss_fn)
            else:
                self.grad_fn = engine.build_group_grad(loss_fn)
        else:
            self.group_size = 1
            self.nb_units = self.nb_workers
            self.grad_fn = engine.build_worker_grad(loss_fn)
        self.agg_fn = engine.build_bounded_aggregate(
            tx, params_template,
            rows_form="decoded" if self.incremental else "wire",
            stale_reweight=self.stale_reweight,
        )
        self.pool = ThreadPoolExecutor(
            max_workers=self.nb_units, thread_name_prefix="bw-submit"
        )
        # one outstanding submission per unit: a unit still in flight when
        # a new round opens is skipped (= an immediate timeout)
        self._in_flight = [None] * self.nb_units
        self._round = 0
        self._round_lock = threading.Lock()
        self._closed = False
        # the deadline engages from the SECOND round: the first dispatch
        # compiles both executables, and charging the compile against the
        # deadline would time out every worker of step 0 (the perf report
        # excludes the compile step for the same reason)
        self._warm = False
        # one committed miss row + zero loss reused for every missing slot:
        # a NaN row on the dtype wire, a zeroed payload under a codec (its
        # content is irrelevant — the aggregate masks non-valid slots to
        # NaN AFTER decoding; only the pytree structure must match)
        d = sum(
            int(np.prod(np.shape(leaf)))
            for leaf in jax.tree_util.tree_leaves(params_template)
        )
        self.d = d
        # per-submission wire bytes for the round-timeline counter track
        # (the runner's bytes_on_wire_total twin, resolved per ROUND here)
        from .compress import bytes_per_row

        self._row_wire_bytes = bytes_per_row(
            d, dtype=engine.exchange_dtype, codec=self.codec
        )
        row_dtype = np.dtype(engine.exchange_dtype or np.float32)
        if self.codec is not None:
            miss_row = self.codec.payload_zeros(d)
        else:
            miss_row = np.full((d,), np.nan, row_dtype)
        self._nan_template = (np.zeros((), np.float32), miss_row)
        self._zero_row = np.zeros((d,), np.float32)
        if self.topology is not None:
            # late-bind the leaf plane: row width + the worker exchange
            # codec (the tree recomputes and signs the level emissions
            # over exactly these wire rows)
            self.topology.bind(self.nb_workers, d, codec=self.codec)
        # incremental mode: the fold executable + the per-round fresh
        # buffer (engine.build_incremental_fold); the fold is our own
        # dispatch against our own buffer, so it shares no donation race
        # with the submissions
        self._fold_fn = self._fresh_buffer = None
        if self.incremental:
            self._fold_fn, self._fresh_buffer = engine.build_incremental_fold(d)
        self._nan_digest = None
        if self.secure:
            from ..secure.submit import row_digest

            # the digest of the NaN drop row — what "arrived" for a slot
            # nobody submitted; sender and receiver agree by construction,
            # so the host authenticator verifies it without a forgery
            # verdict (a timeout is named by forensics, not by crypto).
            # Digested over the f32 drop row on every wire — under a codec
            # the "row" is a payload pytree, but the drop's wire IMAGE is
            # still the NaN row the aggregate masks in
            import jax.numpy as jnp

            self._nan_digest = np.asarray(jax.device_get(
                row_digest(jnp.full((d,), jnp.nan, jnp.float32))
            ))
        # CLEVER carry for stale infill: the last row each worker actually
        # delivered (post-attack, post-momentum — exactly what the PS
        # received), its submission digest, and its age in rounds.  Host-
        # side: the bounded protocol's reassembly buffer, the per-worker
        # twin of the fused engines' TrainState.carry.
        self._carry = [None] * self.nb_workers
        self._carry_digest = [None] * self.nb_workers
        self._carry_age = np.zeros((self.nb_workers,), np.int64)
        self.timeouts_total = np.zeros((self.nb_workers,), np.int64)
        self.stale_total = np.zeros((self.nb_workers,), np.int64)
        # incremental-overlap accounting (measured, not presumed): a fold
        # issued while >= 1 submission was still pending is OVERLAPPED
        self.folds_total = 0
        self.overlapped_folds_total = 0
        self.last_overlap_fraction = 0.0
        self._c_timeouts = self._c_rounds = self._g_deadline = None
        self._c_late = self._c_stale = None
        self._c_folds = self._c_overlapped = self._g_overlap = None
        if registry is not None:
            self._c_timeouts = registry.counter(
                "straggler_timeouts_total",
                "Worker submissions that missed the step deadline",
                labelnames=("worker",),
            )
            self._c_late = registry.counter(
                "straggler_skipped_rounds_total",
                "Rounds skipped because the worker's previous submission "
                "was still in flight",
                labelnames=("worker",),
            )
            self._c_stale = registry.counter(
                "stale_infill_rows_total",
                "Timed-out submissions replaced by the worker's CLEVER "
                "carry row instead of a NaN drop",
                labelnames=("worker",),
            )
            self._c_rounds = registry.counter(
                "bounded_wait_rounds_total", "Bounded-wait aggregation rounds"
            )
            self._g_deadline = registry.gauge(
                "bounded_wait_deadline_seconds", "Configured step deadline"
            )
            if deadline is not None:
                self._g_deadline.set(float(deadline))
            if self.incremental:
                self._c_folds = registry.counter(
                    "exchange_folds_total",
                    "Submissions folded into the aggregate-side buffer "
                    "as they landed (incremental aggregation)",
                )
                self._c_overlapped = registry.counter(
                    "exchange_overlapped_folds_total",
                    "Incremental folds issued while at least one "
                    "submission was still outstanding",
                )
                self._g_overlap = registry.gauge(
                    "exchange_overlap_fraction",
                    "Last round's overlapped-fold fraction",
                )

    # ------------------------------------------------------------------ #

    def _unit_workers(self, unit):
        k = self.group_size
        return range(unit * k, (unit + 1) * k)

    def _track_name(self, unit):
        """Perfetto track name of one submission unit (zero-padded so the
        tracks sort numerically)."""
        label = "submesh" if self.grouped else "worker"
        return "%s %0*d" % (label, len(str(max(self.nb_units - 1, 1))), unit)

    def _submit_one(self, round_id, step_idx, unit, round_begin, args):
        """Submission-thread body: injected stall, then dispatch + drain.
        Returns ``(arrival_seconds, outputs)`` or None when the round
        already closed (the dispatch would read donated buffers).  A
        submission that fails raises — MID-ROUND failures surface at this
        round's barrier, and a failure AFTER the round closed (anything
        but the donation race, which is filtered) surfaces at the NEXT
        round's dispatch — never masquerading as a timeout."""
        if self.model is not None:
            # a group is as late as its slowest member (its submission
            # completes when every vmapped worker's gradient does).  Sleep
            # in slices with a poison check: a lognormal-jitter tail draw
            # is unbounded (minutes at z=3), and one uninterruptible
            # time.sleep would outlive close()'s bounded join and hang
            # interpreter exit on the pool's atexit thread join.
            stall = max(
                self.model.delay(step_idx, w) for w in self._unit_workers(unit)
            )
            if stall:
                tracer = trace.installed()
                stall_t0 = tracer.now_us() if tracer is not None else 0.0
                wake_at = time.monotonic() + stall
                while True:
                    remaining = wake_at - time.monotonic()
                    if remaining <= 0:
                        break
                    time.sleep(min(0.05, remaining))
                    if self._closed:
                        return None
                if tracer is not None:
                    # the injected stall on the unit's own track, UNDER the
                    # round's "submit" span: a straggling round's timeline
                    # shows where the wait actually went
                    tracer.complete_at(
                        "stall", stall_t0, tracer.now_us() - stall_t0,
                        tracer.track(self._track_name(unit)),
                        cat="bounded", args={"step": step_idx},
                    )
        with self._round_lock:
            if round_id != self._round:
                return None  # round closed while we stalled: don't dispatch
            out = self.grad_fn(*args)
        try:
            host = jax.block_until_ready(out)
        except Exception as exc:
            with self._round_lock:
                late = round_id != self._round
            if late and _is_donation_race(exc):
                # buffers reclaimed under a concurrently-closed round:
                # the donation race, not a worker failure
                return None
            raise
        return time.monotonic() - round_begin, host

    def __call__(self, state, batch):
        if self._closed:
            raise RuntimeError("BoundedWaitStep was closed")
        n, k = self.nb_workers, self.group_size
        if self.momentum or self.ef:
            from jax.sharding import NamedSharding, PartitionSpec

            replicated = NamedSharding(self.engine.mesh, PartitionSpec())
            # one-time re-placement (round 0): init_state worker-shards
            # the (n, d) side buffers for the fused shard_map dataflow,
            # but the bounded executables are plain jits whose outputs
            # canonicalize to replicated — one layout for every round
            # keeps the steady-state compile count at 1
            if (self.momentum
                    and state.momentum.sharding.spec != PartitionSpec()):
                state = state.replace(
                    momentum=jax.device_put(state.momentum, replicated)
                )
            if self.ef and state.ef.sharding.spec != PartitionSpec():
                state = state.replace(ef=jax.device_put(state.ef, replicated))
        # the previous dispatch materialized the step counter; this read is
        # a host copy, not a device sync
        step_idx = int(jax.device_get(state.step))
        params, rng = state.params, state.rng
        futures, skipped = {}, []
        round_begin = time.monotonic()
        # per-round submission timeline (docs/observability.md "Reading a
        # round timeline"): the round's open instant on the tracer clock —
        # arrival DELTAS (monotonic) lay each unit's submit span onto its
        # own named track after the barrier closes
        tracer = trace.installed()
        round_t0_us = tracer.now_us() if tracer is not None else 0.0
        for unit in range(self.nb_units):
            prev = self._in_flight[unit]
            if prev is not None and not prev.done():
                # still submitting a previous round: this unit misses the
                # current one outright (bounded queue, see module docstring)
                skipped.append(unit)
                continue
            if prev is not None and not prev.cancelled():
                exc = prev.exception()
                if exc is not None:
                    # a submission that outlived its round and then hit a
                    # REAL failure (_submit_one filtered the donation
                    # race): its round's barrier already closed booking it
                    # a timeout, so surface the error here, at the first
                    # dispatch that sees the dead unit — not silently
                    # re-booking it as a straggler forever
                    raise RuntimeError(
                        "bounded-wait: submission unit %d died after its "
                        "round closed (late failure, not the donation "
                        "race)" % unit
                    ) from exc
            if self.grouped:
                # group mode keeps the leading worker axis (k rows, vmapped
                # inside the group executable — even at k = 1)
                unit_batch = jax.tree_util.tree_map(
                    lambda x, _u=unit: x[_u * k:(_u + 1) * k], batch)
            else:
                unit_batch = jax.tree_util.tree_map(
                    lambda x, _w=unit: x[_w], batch)
            args = [params, unit_batch, rng, step_idx, unit]
            if self.momentum:
                args += [state.momentum, state.momentum_steps]
            if self.ef:
                args += [state.ef]
            self._in_flight[unit] = self.pool.submit(
                self._submit_one, self._round, step_idx, unit, round_begin,
                args,
            )
            futures[unit] = self._in_flight[unit]
        was_warm = self._warm
        if was_warm:
            if self.controller is not None:
                deadline = self.controller.window
            else:
                deadline = self.deadline
        else:
            deadline = None
        self._warm = True
        # incremental mode: fold each submission into the round's buffer
        # the instant its future completes — while its peers are still
        # computing/stalling, which is what "overlap" measures.  A fold
        # that fails (worker death) is left for the barrier loop below to
        # surface; a fold issued when no submission is pending anymore is
        # counted but not overlapped.
        buffer = self._fresh_buffer() if self.incremental else None
        folded = set()
        nb_folds = nb_overlapped = 0
        fut_unit = {fut: unit for unit, fut in futures.items()}

        def fold_done(done, pending):
            nonlocal buffer, nb_folds, nb_overlapped
            for fut in done:
                if fut.cancelled() or fut.exception() is not None:
                    continue  # the barrier loop surfaces worker deaths
                result = fut.result()
                if result is None:
                    continue
                _, out = result
                buffer = self._fold_fn(buffer, out["row"], fut_unit[fut])
                folded.add(fut_unit[fut])
                nb_folds += 1
                nb_overlapped += bool(pending)
                if tracer is not None:
                    # the as-rows-land fold instant on the unit's track —
                    # what makes PR 14's overlap VISIBLE per round
                    tracer.complete_at(
                        "fold", tracer.now_us(), 0.0,
                        tracer.track(self._track_name(fut_unit[fut])),
                        cat="bounded",
                        args={"step": step_idx, "overlapped": bool(pending)},
                    )

        with trace.span("bounded_wait.collect", cat="train"):
            pending = set(futures.values())
            if deadline is None and not self.incremental:
                if pending:
                    wait(pending)
            else:
                deadline_at = (
                    None if deadline is None else time.monotonic() + deadline
                )
                while pending:
                    if deadline_at is None:
                        done, pending = wait(
                            pending, return_when=FIRST_COMPLETED
                        )
                    else:
                        remaining = deadline_at - time.monotonic()
                        if remaining <= 0:
                            break
                        done, pending = wait(
                            pending, timeout=remaining,
                            return_when=FIRST_COMPLETED,
                        )
                    if self.incremental:
                        fold_done(done, pending)
        # close the round: submissions that wake up from now on must not
        # dispatch against buffers the aggregate below will donate
        with self._round_lock:
            self._round += 1
        arrived = np.zeros((n,), bool)
        stale = np.zeros((n,), bool)
        arrival_seconds = np.full((n,), np.inf)
        losses, rows = [None] * n, [None] * n
        mom_rows = [None] * n if self.momentum else None
        ef_rows = [None] * n if self.ef else None
        digests = [None] * n if self.secure else None
        for unit in range(self.nb_units):
            fut = futures.get(unit)
            result = None
            if fut is not None and fut.done():
                try:
                    result = fut.result()
                except Exception as exc:
                    # a worker thread died MID-ROUND (not the donation
                    # race, _submit_one filtered that): surface it here at
                    # the barrier instead of silently counting a timeout
                    raise RuntimeError(
                        "bounded-wait: submission unit %d died mid-round at "
                        "step %d" % (unit, step_idx)
                    ) from exc
            for j, w in enumerate(self._unit_workers(unit)):
                if result is not None:
                    arrival, out = result
                    arrived[w] = True
                    arrival_seconds[w] = arrival
                    grouped = self.grouped
                    losses[w] = out["loss"][j] if grouped else out["loss"]
                    row = out["row"][j] if grouped else out["row"]
                    rows[w] = row
                    if self.stale_infill:
                        # the carry pins a duplicate (n, d) buffer on
                        # device — only pay for it when infill can read it
                        self._carry[w] = row
                        self._carry_age[w] = 0
                    if self.momentum:
                        mom_rows[w] = (
                            out["momentum"][j] if grouped else out["momentum"]
                        )
                    if self.ef:
                        # flat-only (codec exchange refuses grouped mode)
                        ef_rows[w] = out["ef"]
                    if self.secure:
                        digest = out["digest"][j] if grouped else out["digest"]
                        digests[w] = digest
                        if self.stale_infill:
                            self._carry_digest[w] = digest
                else:
                    self._carry_age[w] += 1
                    losses[w] = self._nan_template[0]
                    if (self.stale_infill and self._carry[w] is not None
                            and self._carry_age[w] <= self.stale_max_age):
                        # stale infill: the carry re-enters aggregation —
                        # and spends the f budget (module docstring)
                        stale[w] = True
                        rows[w] = self._carry[w]
                        if self.secure:
                            digests[w] = self._carry_digest[w]
                    else:
                        rows[w] = self._nan_template[1]
                        if self.secure:
                            digests[w] = self._nan_digest
                    if self.momentum:
                        # content never read: the aggregate keeps the old
                        # momentum row wherever ``arrived`` is False
                        mom_rows[w] = self._zero_row
                    if self.ef:
                        # content never read (same mask as momentum)
                        ef_rows[w] = self._zero_row
        if self.incremental:
            # barrier-side completion of the buffer: submissions that
            # landed between the deadline expiring and the round closing
            # were never folded (count them, not overlapped), and stale
            # carries re-enter through the same fold (decode included)
            for w in range(n):
                if arrived[w] and w not in folded:
                    buffer = self._fold_fn(buffer, rows[w], w)
                    nb_folds += 1
                elif stale[w]:
                    buffer = self._fold_fn(buffer, rows[w], w)
                    nb_folds += 1
            self.folds_total += nb_folds
            self.overlapped_folds_total += nb_overlapped
            self.last_overlap_fraction = (
                nb_overlapped / nb_folds if nb_folds else 0.0
            )
        self.timeouts_total += ~arrived
        self.stale_total += stale
        skipped_units = set(skipped)
        if tracer is not None:
            # retrospective per-unit tracks: each unit's round outcome as
            # one span from the round's open — dispatch+encode+compute
            # bounded by the arrival (an injected stall shows as its own
            # "stall" span inside), a miss as the full window it was given
            close_us = tracer.now_us()
            k = self.group_size
            window_us = (
                close_us - round_t0_us if deadline is None
                else float(deadline) * 1e6
            )
            for unit in range(self.nb_units):
                w0 = unit * k
                track = tracer.track(self._track_name(unit))
                if arrived[w0]:
                    tracer.complete_at(
                        "submit", round_t0_us, arrival_seconds[w0] * 1e6,
                        track, cat="bounded", args={"step": step_idx},
                    )
                elif unit in skipped_units:
                    tracer.complete_at(
                        "skipped_round", round_t0_us, 0.0, track,
                        cat="bounded", args={"step": step_idx},
                    )
                elif stale[w0]:
                    span_args = {
                        "step": step_idx,
                        "age": int(self._carry_age[w0]),
                    }
                    if self.stale_reweight:
                        span_args["coefficient"] = (
                            1.0 / (1.0 + float(self._carry_age[w0]))
                        )
                    tracer.complete_at(
                        "stale_infill", round_t0_us, window_us, track,
                        cat="bounded", args=span_args,
                    )
                else:
                    span_args = {"step": step_idx}
                    if self.grouped:
                        # a submesh misses as a unit: all k rows forfeited
                        span_args["forfeited"] = k
                    tracer.complete_at(
                        "timeout", round_t0_us, window_us, track,
                        cat="bounded", args=span_args,
                    )
            # per-round counter tracks: where a straggling round's wall
            # time went, as numbers Perfetto graphs next to the tracks
            if deadline is not None:
                tracer.counter("bounded.deadline_window_s", float(deadline),
                               ts=close_us, cat="bounded")
            tracer.counter("bounded.arrivals", int(arrived.sum()),
                           ts=close_us, cat="bounded")
            tracer.counter("bounded.timeouts", int((~arrived).sum()),
                           ts=close_us, cat="bounded")
            tracer.counter("bounded.stale_rows", int(stale.sum()),
                           ts=close_us, cat="bounded")
            tracer.counter(
                "bounded.bytes_on_wire",
                int(arrived.sum()) * self._row_wire_bytes,
                ts=close_us, cat="bounded",
            )
            if self.incremental:
                tracer.counter("bounded.overlap_fraction",
                               self.last_overlap_fraction,
                               ts=close_us, cat="bounded")
        if ((~arrived).any() or stale.any() or skipped_units) and was_warm:
            # journal (obs/events.py): a round that timed someone out,
            # infilled a stale carry or skipped an in-flight unit is a
            # DECISION (it spent f budget); calm rounds stay off the
            # timeline, and the compile round's arrivals measure XLA
            events.emit(
                "bounded_round", step=step_idx,
                deadline_s=None if deadline is None else float(deadline),
                nb_arrived=int(arrived.sum()),
                timed_out=[int(w) for w in np.nonzero(~arrived)[0]],
                stale_infill=[int(w) for w in np.nonzero(stale)[0]],
                skipped_units=sorted(int(u) for u in skipped_units),
            )
        if was_warm and self.stale_reweight:
            # each reweighted re-entry is its own typed event: the age and
            # coefficient the aggregate applied (the in-graph twin is
            # metrics["stale_reweight_coeff"])
            for w in np.nonzero(stale)[0]:
                age = int(self._carry_age[w])
                events.emit(
                    "stale_reweight", step=step_idx, worker=int(w),
                    age=age, coefficient=1.0 / (1.0 + age),
                )
        if was_warm and self.grouped:
            # a submesh that missed its window forfeited its k rows as a
            # unit (skipped units are named by bounded_round instead: they
            # never dispatched, so no deadline judged them)
            for unit in range(self.nb_units):
                if unit in skipped_units or arrived[unit * self.group_size]:
                    continue
                events.emit(
                    "submesh_timeout", step=step_idx, group=int(unit),
                    forfeited=int(self.group_size),
                )
        if self.controller is not None and was_warm:
            # feed the controller only rounds the deadline governed (the
            # compile round's arrivals measure XLA, not the fleet); a
            # grouped round's arrivals are per-UNIT decisions, so the
            # percentile votes over units, not over duplicated members
            self.controller.observe_round(
                arrival_seconds, step=step_idx,
                unit_size=self.group_size if self.grouped else 1,
            )
        if self._c_folds is not None:
            self._c_folds.inc(nb_folds)
            self._c_overlapped.inc(nb_overlapped)
            self._g_overlap.set(self.last_overlap_fraction)
        if self._c_timeouts is not None:
            for w in np.nonzero(~arrived)[0]:
                self._c_timeouts.labels(worker=str(int(w))).inc()
            for w in np.nonzero(stale)[0]:
                self._c_stale.labels(worker=str(int(w))).inc()
            for unit in skipped:
                for w in self._unit_workers(unit):
                    self._c_late.labels(worker=str(int(w))).inc()
            self._c_rounds.inc()
            if self._g_deadline is not None and deadline is not None:
                self._g_deadline.set(float(deadline))
        import jax.numpy as jnp

        extras = {}
        if self.stale_reweight:
            # the (n,) age vector the aggregate's traced coefficient reads
            # — ages tick host-side, the operand shape/dtype never moves
            extras["stale_age"] = jnp.asarray(
                self._carry_age.astype(np.int32)
            )
        if self.momentum:
            extras["momentum"] = jnp.stack(mom_rows)
        if self.ef:
            extras["ef"] = jnp.stack(ef_rows)
        if self.secure:
            extras["digests"] = jnp.stack(digests)
        if self.incremental:
            rows_in = buffer  # already decoded, rows_form="decoded"
        else:
            # tree-stack: plain (d,) rows on the dtype wire, the encoded
            # payload pytrees under a codec (decoded inside the aggregate)
            rows_in = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *rows
            )
            if self.topology is not None:
                # the tree protocol (topology/tree.py): per-level bounded
                # wait + custody over the stacked wire rows.  Runs AFTER
                # the worker-plane bookkeeping above (timeout counters,
                # tracer, bounded_round, the leaf controller) — those
                # describe what the WORKERS did; the masks below are what
                # the aggregate consumes, with excluded subtrees cleared
                # (their NaN infill spends the declared per-level budget)
                with trace.span("bounded_wait.topology", cat="train",
                                step=step_idx):
                    arrived, stale = self.topology.process_round(
                        step_idx, arrived, stale, arrival_seconds, rows_in,
                        leaf_window=deadline,
                    )
        with trace.span("bounded_wait.aggregate", cat="train", step=step_idx):
            return self.agg_fn(
                state, rows_in, jnp.stack(losses),
                jnp.asarray(arrived), jnp.asarray(stale), extras,
            )

    def _cache_size(self):
        """Compile-count surface for the zero-recompile assertions AND the
        runner's CompileWatch: the MAX over the bounded-wait executables
        (submission, aggregate and — incremental mode — the fold), so
        steady state reads 1 like every fused step (a sum would read 2+
        and trip the watch's cache_size > 1 retrace alarm on the expected
        first compile)."""
        sizes = [self.grad_fn._cache_size(), self.agg_fn._cache_size()]
        if self._fold_fn is not None:
            sizes.append(self._fold_fn._cache_size())
        if self.topology is not None:
            sizes.append(self.topology.cache_size())
        return max(sizes)

    def close(self, timeout=5.0):
        """Idempotent shutdown: poison the round id so stalled submission
        threads never dispatch against freed buffers, cancel everything
        queued, then JOIN the outstanding threads with a bounded wait (a
        stalled sleep must not leak a thread holding engine buffers past
        the step's lifetime — nor hang shutdown forever)."""
        if self._closed:
            return
        self._closed = True
        with self._round_lock:
            self._round += 1
        self.pool.shutdown(wait=False, cancel_futures=True)
        pending = [
            fut for fut in self._in_flight
            if fut is not None and not fut.done()
        ]
        if pending:
            wait(pending, timeout=timeout)

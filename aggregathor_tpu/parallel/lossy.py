"""Lossy-link simulator: UDP packet-loss semantics as NaN masking.

The reference's patched transport ships gradients in <=65000-byte UDP
datagrams and fills lost packets with NaN bytes on the parameter server
(mpi_rendezvous_mgr.patch:585-627, NaN fill at 833-841), with env knobs
``USE_UDP`` / ``UDP_WORKERS`` (only the first k workers are lossy, and only
for tensors above ~1 MB; patch:507-513).  ICI is reliable, so on TPU this
becomes an explicit simulation: per step, each lossy worker drops whole
"packets" (contiguous coordinate runs sized like a UDP datagram) i.i.d. with
the configured rate, and dropped runs become NaN — which the NaN-aware GARs
(average-nan, median, the +inf-distance convention of Krum/Bulyan) absorb,
exactly the reference's failure mode.

The ``clever`` mode reproduces ``CLEVER=1`` (patch:833-835): a lost packet
keeps the previous step's value instead of NaN — the PS's reassembly buffer
simply retains last step's bytes where nothing arrived.  The engine carries
the per-worker previously-received gradients in ``TrainState.carry``
(worker-sharded, so the (n, d) matrix never lands on one device) and
supplies each worker's row via ``previous=``.

**Ordering under a compressed exchange** (parallel/compress.py): the wire
codec encodes/decodes BEFORE this module's masking runs — a dropped packet
drops ENCODED bytes, so the NaN runs must land on the DECODED row image
(``RobustEngine._perturb_local`` applies codec -> lossy in that order).
The inverse order would be wrong two ways: masking the pre-encode row would
let int8's per-row scale read the NaN (``max|row|`` of a NaN row is NaN),
poisoning the WHOLE row instead of one packet run, and top-k would
transmit the NaN coordinates as its largest magnitudes — a single lost
datagram silently consuming the entire sparsity budget.  A dropped packet
of int8 payload is still a NaN coordinate run, exactly this module's
semantics (regression-pinned by tests/test_compress.py).
"""

import jax
import jax.numpy as jnp

from ..utils import parse_keyval

# 65000-byte datagrams of float32 coordinates (patch:555-573)
PACKET_COORDS = 65000 // 4
# UDP engages only above ~1 MB tensors in the reference (patch:507-513)
MIN_LOSSY_COORDS = (1 << 20) // 4


class LossyLink:
    """Deterministic packet-loss NaN masking for the first ``nb_lossy`` workers."""

    def __init__(self, nb_lossy, args=None):
        kv = parse_keyval(args or [], {
            "drop-rate": 0.01,
            "packet-coords": PACKET_COORDS,
            "min-coords": MIN_LOSSY_COORDS,
            "clever": False,
        })
        self.nb_lossy = int(nb_lossy)
        self.drop_rate = float(kv["drop-rate"])
        self.packet_coords = int(kv["packet-coords"])
        self.min_coords = int(kv["min-coords"])
        self.clever = bool(kv["clever"])

    def apply(self, grad, key, worker_index, previous=None, drop_rate=None):
        """Mask lost packets of one worker's (d,) gradient.

        Applies only when ``worker_index < nb_lossy`` and the gradient is
        large enough to have used the lossy transport.  ``previous`` supplies
        the stale infill for clever mode.  ``drop_rate`` overrides the
        static configured rate with a (possibly traced) per-step value —
        the chaos scheduler's hook for loss storms that vary by regime
        without recompiling (``chaos/schedule.py``).
        """
        d = grad.shape[0]
        if self.nb_lossy <= 0 or d < self.min_coords:
            return grad
        rate = self.drop_rate if drop_rate is None else drop_rate
        nb_packets = -(-d // self.packet_coords)
        drops = jax.random.bernoulli(key, rate, (nb_packets,))
        mask = jnp.repeat(drops, self.packet_coords, total_repeat_length=nb_packets * self.packet_coords)[:d]
        if self.clever and previous is None:
            from ..utils import UserException

            raise UserException(
                "LossyLink clever:true needs the previous gradient; run it through "
                "RobustEngine (which carries it in TrainState.carry) or pass previous="
            )
        infill = previous if self.clever else jnp.full_like(grad, jnp.nan)
        lossy = jnp.where(mask, infill, grad)
        return jnp.where(worker_index < self.nb_lossy, lossy, grad)

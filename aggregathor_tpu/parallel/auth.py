"""Host-level worker authentication (off the hot path).

The reference's threat model includes *forged messages*: Byzantine workers
may try to impersonate honest ones, so the patched transport ed25519-signs
every worker->PS tensor push and the PS verifies before reassembly
(tf_patches/patches/mpi_rendezvous_mgr.patch:585-627, 777-781, 1057-1064);
TLS channel credentials cover the control plane
(tf_patches/patches/grpc_channel.patch:70-85).

TPU-native mapping (SURVEY.md §2.6): inside a slice, the ICI fabric is
closed hardware — a worker cannot inject traffic as another chip, so per-step
signatures add nothing. The boundary that still needs authentication is the
*host* layer: multi-host coordination traffic, checkpoint/restore blobs, and
any gradient material that leaves the SPMD program (e.g. host-relayed DCN
setups). This module provides the primitive: HMAC-SHA256 tags under
per-worker keys derived from one session secret, verified in constant time.
Checkpoint snapshots are tagged/verified when ``obs.Checkpoints`` is built
with ``authenticator=``; other host flows can reuse the same object.
Symmetric (not ed25519) because the single controller already shares a
secret with every worker host it launched — there is no third-party
verification requirement. The C++ implementation (ops/native/auth.cpp)
exists for native-tier parity with the reference's C++/libsodium signing
layer and for hosts whose Python lacks an accelerated hashlib; the stdlib
fallback keeps the API identical where the library cannot build. For the
control plane, JAX's multi-host runtime rides gRPC — enabling TLS there is
deployment configuration, documented in docs/transport.md.
"""

import hashlib
import hmac as _py_hmac
import struct

from ..ops import native


def _native_ok():
    try:
        return native.available()
    except Exception:
        return False


def derive_worker_key(session_secret, worker_index):
    """Per-worker key = SHA-256(secret || worker_index), like the reference
    derives per-worker identities from deploy-time provisioning."""
    material = bytes(session_secret) + struct.pack("<q", int(worker_index))
    if _native_ok():
        return native.sha256(material)
    return hashlib.sha256(material).digest()


def _message(worker_index, step, payload):
    # Binding the (worker, step) header into the tag prevents replaying one
    # worker's gradient as another's or re-sending a stale step — the same
    # properties the reference gets from signing the metadata chunk
    # (mpi_rendezvous_mgr.patch:585-627).
    return struct.pack("<qq", int(worker_index), int(step)) + bytes(payload)


class GradientAuthenticator:
    """Signs / verifies per-worker byte payloads with per-worker HMAC keys."""

    def __init__(self, session_secret, nb_workers):
        self.nb_workers = int(nb_workers)
        self.keys = [derive_worker_key(session_secret, w) for w in range(self.nb_workers)]

    def sign(self, worker_index, step, payload):
        """32-byte tag for ``payload`` (bytes) from ``worker_index`` at ``step``."""
        if not 0 <= int(worker_index) < self.nb_workers:
            raise ValueError(
                "worker_index %r out of range [0, %d)" % (worker_index, self.nb_workers)
            )
        msg = _message(worker_index, step, payload)
        if _native_ok():
            return native.hmac_sha256(self.keys[worker_index], msg)
        return _py_hmac.new(self.keys[worker_index], msg, hashlib.sha256).digest()

    def verify(self, worker_index, step, payload, tag):
        """Constant-time check; False for bad index, stale step binding, or forgery."""
        if not 0 <= int(worker_index) < self.nb_workers:
            return False
        msg = _message(worker_index, step, payload)
        if _native_ok():
            return native.hmac_verify(self.keys[worker_index], msg, tag)
        expect = _py_hmac.new(self.keys[worker_index], msg, hashlib.sha256).digest()
        return _py_hmac.compare_digest(expect, bytes(tag))

"""Host-level worker authentication (off the hot path).

The reference's threat model includes *forged messages*: Byzantine workers
may try to impersonate honest ones, so the patched transport ed25519-signs
every worker->PS tensor push and the PS verifies before reassembly
(tf_patches/patches/mpi_rendezvous_mgr.patch:585-627, 777-781, 1057-1064);
TLS channel credentials cover the control plane
(tf_patches/patches/grpc_channel.patch:70-85).

TPU-native mapping (SURVEY.md §2.6): inside a slice, the ICI fabric is
closed hardware — a worker cannot inject traffic as another chip, so per-step
signatures add nothing. The boundary that still needs authentication is the
*host* layer: multi-host coordination traffic, checkpoint/restore blobs, and
any gradient material that leaves the SPMD program (e.g. host-relayed DCN
setups). This module provides the primitive: HMAC-SHA256 tags under
per-worker keys derived from one session secret, verified in constant time.
Checkpoint snapshots are tagged/verified when ``obs.Checkpoints`` is built
with ``authenticator=``; other host flows can reuse the same object.
Symmetric (not ed25519) because the single controller already shares a
secret with every worker host it launched — there is no third-party
verification requirement. The C++ implementation (ops/native/auth.cpp)
exists for native-tier parity with the reference's C++/libsodium signing
layer and for hosts whose Python lacks an accelerated hashlib; the stdlib
fallback keeps the API identical where the library cannot build. For the
control plane: the runtime's own coordination channel exposes no TLS knob to
guest code (docs/transport.md "In-flight closure"), so every payload THIS
framework puts on the wire is encrypted-then-MACed under the session secret
(``authenticate_processes``) — channel security for the runtime's internal
traffic remains deployment configuration.
"""

import hashlib
import hmac as _py_hmac
import struct

from ..ops import native


def _native_ok():
    try:
        return native.available()
    except Exception:
        return False


def derive_worker_key(session_secret, worker_index, context=b"gradient"):
    """Per-worker key = SHA-256(secret || context || worker_index), like the
    reference derives per-worker identities from deploy-time provisioning.

    ``context`` domain-separates uses of the one session secret: without it
    the checkpoint-tag key (worker 0) would equal process 0's bring-up
    handshake key, and a 32-byte checkpoint body at a matching step could
    cross-verify between the two protocols."""
    material = (
        bytes(session_secret)
        + struct.pack("<q", len(context)) + bytes(context)
        + struct.pack("<q", int(worker_index))
    )
    if _native_ok():
        return native.sha256(material)
    return hashlib.sha256(material).digest()


def derive_worker_key_legacy(session_secret, worker_index):
    """The pre-context-separation derivation (secret || index, no context).

    Kept ONLY so snapshots tagged before the domain-separation fix can be
    verified once at restore and re-tagged under the current scheme on the
    next save — never used for signing new material."""
    material = bytes(session_secret) + struct.pack("<q", int(worker_index))
    if _native_ok():
        return native.sha256(material)
    return hashlib.sha256(material).digest()


def _message(worker_index, step, payload):
    # Binding the (worker, step) header into the tag prevents replaying one
    # worker's gradient as another's or re-sending a stale step — the same
    # properties the reference gets from signing the metadata chunk
    # (mpi_rendezvous_mgr.patch:585-627).
    return struct.pack("<qq", int(worker_index), int(step)) + bytes(payload)


class GradientAuthenticator:
    """Signs / verifies per-worker byte payloads with per-worker HMAC keys.

    ``context`` names the protocol this instance serves (``b"gradient"``,
    ``b"ckpt"``, ``b"handshake"``, ...); instances with different contexts
    derive disjoint key families from the same session secret, so a tag
    minted under one protocol can never verify under another."""

    def __init__(self, session_secret, nb_workers, context=b"gradient"):
        self.nb_workers = int(nb_workers)
        self.keys = [
            derive_worker_key(session_secret, w, context=context)
            for w in range(self.nb_workers)
        ]
        # kept only for verify_legacy's one-time migration path
        self._secret = bytes(session_secret)

    def sign(self, worker_index, step, payload):
        """32-byte tag for ``payload`` (bytes) from ``worker_index`` at ``step``."""
        if not 0 <= int(worker_index) < self.nb_workers:
            raise ValueError(
                "worker_index %r out of range [0, %d)" % (worker_index, self.nb_workers)
            )
        msg = _message(worker_index, step, payload)
        if _native_ok():
            return native.hmac_sha256(self.keys[worker_index], msg)
        return _py_hmac.new(self.keys[worker_index], msg, hashlib.sha256).digest()

    def verify(self, worker_index, step, payload, tag):
        """Constant-time check; False for bad index, stale step binding, or forgery."""
        if not 0 <= int(worker_index) < self.nb_workers:
            return False
        msg = _message(worker_index, step, payload)
        if _native_ok():
            return native.hmac_verify(self.keys[worker_index], msg, tag)
        expect = _py_hmac.new(self.keys[worker_index], msg, hashlib.sha256).digest()
        return _py_hmac.compare_digest(expect, bytes(tag))

    def sign_many(self, step, rows):
        """Vectorized hot-path signing: one (n, d) stack -> (n, 32) uint8 tags.

        Bit-compatible with the single-row API: row ``w``'s tag equals
        ``sign(w, step, rows[w].tobytes())``.  The per-worker keys were
        derived ONCE at construction; this path additionally reuses one
        message buffer across rows (header packed in place, payload copied
        into the same bytearray), so the per-step cost is n HMAC cores and
        nothing else — the discipline the secure submission layer
        (secure/submit.py) leans on every training step."""
        import numpy as np

        rows = np.ascontiguousarray(rows)
        if rows.shape[0] != self.nb_workers:
            raise ValueError(
                "sign_many got %d rows for %d workers" % (rows.shape[0], self.nb_workers)
            )
        row_bytes = rows.nbytes // self.nb_workers if self.nb_workers else 0
        flat = rows.reshape(self.nb_workers, -1).view(np.uint8).reshape(
            self.nb_workers, row_bytes
        )
        tags = np.empty((self.nb_workers, 32), np.uint8)
        message = bytearray(16 + row_bytes)
        use_native = _native_ok()
        for worker in range(self.nb_workers):
            struct.pack_into("<qq", message, 0, worker, int(step))
            message[16:] = flat[worker].tobytes()
            if use_native:
                tag = native.hmac_sha256(self.keys[worker], bytes(message))
            else:
                tag = _py_hmac.new(
                    self.keys[worker], bytes(message), hashlib.sha256
                ).digest()
            tags[worker] = np.frombuffer(tag, np.uint8)
        return tags

    def verify_many(self, step, rows, tags):
        """Vectorized verification: (n, d) stack + (n, 32) tags -> (n,) bool.

        Constant-time per row (``compare_digest`` on the recomputed tag);
        bit-compatible with ``verify`` row by row."""
        import numpy as np

        expect = self.sign_many(step, rows)
        tags = np.ascontiguousarray(tags).reshape(self.nb_workers, -1)
        ok = np.empty((self.nb_workers,), bool)
        for worker in range(self.nb_workers):
            ok[worker] = _py_hmac.compare_digest(
                expect[worker].tobytes(), tags[worker].tobytes()
            )
        return ok

    def verify_legacy(self, worker_index, step, payload, tag):
        """Verify under the pre-context-separation key derivation.

        Migration path only: lets a restore accept a snapshot tagged by the
        old scheme exactly once (the caller should warn, and the next save
        re-tags under the current keys). Never used to MINT tags."""
        if not 0 <= int(worker_index) < self.nb_workers:
            return False
        key = derive_worker_key_legacy(self._secret, worker_index)
        msg = _message(worker_index, step, payload)
        if _native_ok():
            return native.hmac_verify(key, msg, tag)
        expect = _py_hmac.new(key, msg, hashlib.sha256).digest()
        return _py_hmac.compare_digest(expect, bytes(tag))


def state_digest(params):
    """SHA-256 over this process' addressable parameter bytes, leaves in
    pytree order, shards in index order — the material every host must hold
    before the first training collective."""
    import jax
    import numpy as np

    digest = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(params):
        shards = sorted(leaf.addressable_shards, key=lambda s: s.index)
        for shard in shards:
            digest.update(np.ascontiguousarray(np.asarray(shard.data)).tobytes())
    return digest.digest()


def authenticate_processes(session_secret, params, step=0, verify_equal=True):
    """Authenticate the multi-host boundary before training collectives.

    The reference signs every worker->PS push and verifies at the PS
    (mpi_rendezvous_mgr.patch:585-627, 1057-1064); under single-controller
    SPMD the per-step hot path is ICI hardware, so the surface that needs
    the equivalent check is process bring-up: every participating process
    proves knowledge of the shared session secret by HMAC-tagging a digest
    of its post-init (post-restore) parameter bytes under its per-process
    key, all (digest, tag) pairs are exchanged, and every process verifies
    every other's tag.  A process launched without the secret — or one whose
    payload was tampered in flight — cannot produce a valid tag and the
    whole cluster aborts loudly instead of training with it.

    ``verify_equal`` additionally asserts all digests are identical —
    correct for replicated layouts (the flat engine); sharded layouts hold
    different bytes per host and skip it.

    In-flight confidentiality: the exchanged payload is the digest
    ENCRYPTED under a context-separated key from the same secret
    (encrypt-then-MAC — the tag covers the ciphertext), so the framework's
    own cross-host control material is confidential and authenticated
    end-to-end regardless of the underlying channel's security.  The
    runtime's OWN coordination channel cannot be TLS'd from guest code
    (docs/transport.md "In-flight closure"); this covers every byte this
    framework chooses to put on the wire — the reference's TLS patch
    protected the same class of payloads (grpc_channel.patch:70-85).

    Raises ``UserException`` naming the offending ranks.
    """
    import jax
    import numpy as np

    from ..utils import UserException

    nb, pid = jax.process_count(), jax.process_index()
    auth = GradientAuthenticator(session_secret, nb, context=b"handshake")
    from .crypto import SnapshotCipher

    cipher = SnapshotCipher(session_secret, context=b"handshake-enc")
    digest = state_digest(params)
    ct = cipher.encrypt(step, digest)
    ct_len = len(ct)  # deterministic: MAGIC + nonce + SENTINEL + 32
    tag = auth.sign(pid, step, ct)
    mine = np.frombuffer(ct + tag, np.uint8)
    if nb == 1:
        gathered = mine[None]
    else:
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(mine))

    def _digest_of(rank):
        """Rank's digest if its payload authenticates AND decrypts; None
        otherwise (wrong secret fails the tag already; a tag-valid payload
        that will not decrypt is equally unauthenticated)."""
        row_ct = gathered[rank, :ct_len].tobytes()
        if not auth.verify(rank, step, row_ct, gathered[rank, ct_len:].tobytes()):
            return None
        try:
            return cipher.decrypt(step, row_ct)
        except UserException:
            return None

    digests = {rank: _digest_of(rank) for rank in range(nb)}
    bad = [rank for rank in range(nb) if digests[rank] is None]
    if bad:
        raise UserException(
            "Host authentication FAILED for process(es) %s: payload tampered or "
            "--session-secret mismatch; refusing to train with unauthenticated "
            "hosts (reference parity: mpi_rendezvous_mgr.patch:585-627)"
            % ", ".join(map(str, bad))
        )
    if verify_equal:
        mismatched = [
            rank for rank in range(nb)
            if digests[rank] != digest
        ]
        if mismatched:
            raise UserException(
                "Host state DIVERGED at bring-up: process(es) %s hold different "
                "parameter bytes than process %d (bad restore or nondeterministic "
                "init); collectives would silently corrupt from step one"
                % (", ".join(map(str, mismatched)), pid)
            )
    return nb

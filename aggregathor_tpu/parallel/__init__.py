"""Distributed engine: mesh construction, worker isolation, the sharded GAR
path, Byzantine attack injection and the lossy-link simulator.

This package replaces the reference's entire distribution stack — the
parameter-server cluster manager (cluster.py), the replicated graph
construction (graph.py:204-315) and the gRPC/MPI/UDP transports
(tf_patches/) — with a single-controller JAX SPMD design over a
`jax.sharding.Mesh`:

- ``mesh``:    mesh construction over ICI/DCN with a ``worker`` axis; the
               reference's device allocator (cluster.py:147-221) becomes axis
               sizing over `jax.devices()`.
- ``engine``:  the robust training step.  Per-worker gradients are computed in
               isolation under ``shard_map``; an ``all_to_all`` reshards the
               implicit (n, d) gradient matrix from worker-sharded to
               *dimension-sharded* column blocks; pairwise distances reduce
               with an O(n²) ``psum``; the GAR combine runs blockwise; an
               ``all_gather`` restores the aggregated (d,) vector.  Per-device
               memory stays O(d) and the bytes on the wire are ~2x one
               allreduce — this is the TPU equivalent of the reference's
               worker->PS gradient push (SURVEY.md §2.6).
- ``attacks``: Byzantine gradient attacks applied to a worker's *own* slot
               (implements the runner.py:345 TODO for real).
- ``lossy``:   NaN-masking lossy-link simulator reproducing the UDP
               transport's packet-loss semantics
               (mpi_rendezvous_mgr.patch:833-841).
"""

from .mesh import make_mesh, worker_axis  # noqa: F401
from .engine import RobustEngine, ShardedRobustEngine  # noqa: F401
from . import attacks  # noqa: F401
from . import lossy  # noqa: F401

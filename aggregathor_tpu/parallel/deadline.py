"""Adaptive bounded-wait deadlines: a percentile controller over arrivals.

PR 10's bounded-wait protocol closes every round at a FIXED
``--step-deadline``.  Under a drifting or bimodal straggler regime that
forces a bad trade: a window sized for the tail wastes the common case
(every quiet round still waits the full deadline before giving up on a
genuinely dead worker), while a window sized for the common case throws
away the whole tail.  OptiReduce (arXiv:2310.06993) shows the win comes
from ADAPTIVE time windows; this module is that controller, host-side pure
policy in the watchdog's style (guardian/watchdog.py): it never touches
engines or clocks, it just consumes one arrival vector per round and emits
the next round's window.

Control law, per completed round:

1. The round's per-worker arrival times (seconds from round open to row
   materialization; a worker that missed the window is CENSORED — observed
   only as "later than the window") feed a target: the
   ``percentile``-th percentile of the arrival vector with censored
   entries read as ``+inf``.  If the percentile rank touches a censored
   entry the round's target is the ``ceiling`` — the controller widens
   when it cannot see the tail it is asked to cover.
2. The window moves by an EMA, ``w <- (1 - ema) * w + ema * target``, so
   a single spiked round cannot whipsaw the window (``ema`` is the weight
   of the NEW observation).
3. The result clamps into ``[floor, ceiling]``.  ``at_ceiling`` exposes a
   pinned controller — the last round's DEMANDED target hit the ceiling
   (the EMA'd window only asymptotically approaches it, so the window
   itself would under-report a pinned tail for dozens of rounds) — which
   the guardian treats as an escalation input
   (``Watchdog.observe_ceiling``, docs/guardian.md).

Choosing ``percentile``: a coalition of ``s`` PERSISTENT stragglers
censors ``s/n`` of every round, so any percentile above
``100 * (n - s - 1) / (n - 1)`` reads censored forever and pins the
window at the ceiling (the rank ``P/100 * (n-1)`` interpolates, so its
CEILED neighbor must stay below the censored mass).  Set it at or below
that bound with ``s = f`` — ``100 * (n - f - 1) / (n - 1)``, e.g. 71.4
for n=8, f=2 — and the window converges down to the honest arrivals
instead (the adaptive win the straggler sweep measures,
benchmarks/straggler_sweep.py).

Everything here is deterministic in the observed arrivals — the
percentile/EMA/clamp math is pinned against synthetic traces by
tests/test_deadline.py, no wall clock involved.
"""

import numpy as np

from ..obs import events
from ..utils import UserException

#: arrival-seconds histogram buckets (sub-ms to tens of seconds — the
#: whole range a host-clock round can span)
ARRIVAL_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class DeadlineController:
    """Percentile/EMA/clamp window controller for bounded-wait rounds.

    Args:
      initial: starting window (seconds); clamped into [floor, ceiling].
      percentile: target arrival percentile in (0, 100] the window tracks.
      floor: smallest window the controller may emit (> 0 — a zero window
        would time out every worker of every round).
      ceiling: largest window (defaults to ``initial``) — the operator's
        declared worst-case wait, i.e. what ``--step-deadline`` meant
        under the fixed protocol.
      ema: weight of each new round's target in (0, 1]; 1 disables
        smoothing.
      registry: optional ``MetricsRegistry`` — per-worker arrival
        histograms (``bounded_wait_arrival_seconds{worker=}``), the live
        window gauge (``deadline_controller_window_seconds``), a pinned
        flag (``deadline_controller_at_ceiling``) and the censored-round
        counter (``deadline_controller_censored_rounds_total``).
    """

    def __init__(self, initial, percentile=90.0, floor=0.01, ceiling=None,
                 ema=0.3, registry=None):
        if initial is None or initial <= 0.0:
            raise UserException(
                "the deadline controller needs an initial window > 0 "
                "seconds (--step-deadline)"
            )
        self.percentile = float(percentile)
        if not 0.0 < self.percentile <= 100.0:
            raise UserException(
                "--deadline-percentile must lie in (0, 100], got %g"
                % self.percentile
            )
        self.floor = float(floor)
        if self.floor <= 0.0:
            raise UserException(
                "--deadline-floor must be > 0 seconds (a zero window times "
                "out every worker), got %g" % self.floor
            )
        self.ceiling = float(ceiling) if ceiling is not None else float(initial)
        if self.ceiling < self.floor:
            raise UserException(
                "--deadline-ceiling (%g) must be >= --deadline-floor (%g)"
                % (self.ceiling, self.floor)
            )
        self.ema = float(ema)
        if not 0.0 < self.ema <= 1.0:
            raise UserException(
                "--deadline-ema must lie in (0, 1] (the weight of each new "
                "round's target), got %g" % self.ema
            )
        self._window = float(np.clip(initial, self.floor, self.ceiling))
        # before any observation the demand signal falls back to the
        # window itself (an initial == ceiling reads pinned until the
        # first round proves otherwise)
        self._demand_at_ceiling = self._window >= self.ceiling * (1.0 - 1e-9)
        self.rounds_observed = 0
        self.censored_rounds = 0
        self._h_arrival = self._g_window = None
        self._g_ceiling = self._c_censored = None
        if registry is not None:
            self._h_arrival = registry.histogram(
                "bounded_wait_arrival_seconds",
                "Per-worker submission arrival time within a round",
                labelnames=("worker",), buckets=ARRIVAL_BUCKETS,
            )
            self._g_window = registry.gauge(
                "deadline_controller_window_seconds",
                "Adaptive bounded-wait window for the next round",
            )
            self._g_ceiling = registry.gauge(
                "deadline_controller_at_ceiling",
                "1 while the last round's demanded target sat at the "
                "window ceiling",
            )
            self._c_censored = registry.counter(
                "deadline_controller_censored_rounds_total",
                "Rounds whose target percentile fell among censored "
                "(timed-out) arrivals",
            )
            self._g_window.set(self._window)
            self._g_ceiling.set(float(self.at_ceiling))

    @property
    def window(self):
        """The window (seconds) the NEXT round should close at."""
        return self._window

    @property
    def at_ceiling(self):
        """True while the last round's DEMANDED target sat at/over the
        ceiling — the observed tail wants more than the budgeted window
        (escalation input).  Deliberately not the EMA'd window: the EMA
        only asymptotically approaches the ceiling (>= 58 rounds to close
        a 1e-9 gap at ema 0.3), which would stall the guardian's
        ceiling-patience streak far past its documented length."""
        return self._demand_at_ceiling

    def observe_round(self, arrival_seconds, step=None, unit_size=1):
        """Feed one completed round; returns the updated window.

        ``arrival_seconds`` is the (n,) per-worker arrival vector: seconds
        from round open to row materialization, with non-finite entries
        (NaN/inf) for workers that missed the round's window (censored).
        ``step`` (optional) stamps the journal's ``deadline_window`` events
        — emitted only when the window MOVES materially, censors, or flips
        its at-ceiling verdict, so the journal stays a decision timeline,
        not a per-round metrics mirror.

        ``unit_size`` (bounded-wait v3): the number of logical workers per
        SUBMISSION UNIT.  A grouped round's k members share one arrival
        instant by construction (the submesh arrives — or forfeits — as a
        whole), so the percentile votes over the W per-unit arrivals
        (every k-th entry) instead of k duplicated copies; the per-worker
        histograms keep their full labels.
        """
        arrivals = np.asarray(arrival_seconds, np.float64).reshape(-1)
        finite = np.isfinite(arrivals)
        if self._h_arrival is not None:
            for worker in np.nonzero(finite)[0]:
                self._h_arrival.labels(worker=str(int(worker))).observe(
                    float(arrivals[worker])
                )
        unit_size = int(unit_size)
        if unit_size > 1:
            if arrivals.size % unit_size:
                raise UserException(
                    "observe_round: %d arrivals do not group into units "
                    "of %d" % (arrivals.size, unit_size)
                )
            arrivals = arrivals[::unit_size]
            finite = finite[::unit_size]
        censored = np.sort(np.where(finite, arrivals, np.inf))
        # linear-interpolated percentile, computed by hand so a censored
        # (+inf) upper neighbor reads as "censored" instead of an inf-inf
        # NaN from np.percentile's interpolation
        rank = self.percentile / 100.0 * (censored.size - 1)
        lo, hi = int(np.floor(rank)), int(np.ceil(rank))
        if np.isfinite(censored[hi]):
            frac = rank - lo
            target = float((1.0 - frac) * censored[lo] + frac * censored[hi])
        else:
            target = np.inf
        censored_round = not np.isfinite(target)
        if censored_round:
            # the percentile rank touched a censored arrival: the tail the
            # controller is asked to cover is beyond what it observed, so
            # the round votes for the widest window it is allowed
            target = self.ceiling
            self.censored_rounds += 1
            if self._c_censored is not None:
                self._c_censored.inc()
        # demand, judged on the UNCLAMPED pre-EMA target: the escalation
        # streak must begin the round the tail outgrows the budget
        was_at_ceiling = self._demand_at_ceiling
        previous_window = self._window
        self._demand_at_ceiling = target >= self.ceiling * (1.0 - 1e-9)
        self._window = float(np.clip(
            (1.0 - self.ema) * self._window + self.ema * target,
            self.floor, self.ceiling,
        ))
        self.rounds_observed += 1
        if self._g_window is not None:
            self._g_window.set(self._window)
            self._g_ceiling.set(float(self.at_ceiling))
        # journal (obs/events.py): window MOVES are causal decisions — a
        # material move (>1% relative or >1 ms), a censored target or an
        # at-ceiling flip lands on the timeline; the per-round jitter of
        # the EMA does not
        moved = abs(self._window - previous_window) > max(
            0.01 * previous_window, 1e-3
        )
        if moved or censored_round or was_at_ceiling != self._demand_at_ceiling:
            events.emit(
                "deadline_window", step=step,
                window_s=self._window, previous_s=previous_window,
                target_s=float(target), at_ceiling=bool(self._demand_at_ceiling),
                censored=bool(censored_round), round=int(self.rounds_observed),
            )
        return self._window

"""Fully-sharded robust training engine: logical worker = submesh.

The flat ``RobustEngine`` (engine.py) maps one Byzantine worker to one device
slot and keeps parameters replicated — the right shape for the reference's
CNN-scale experiments. This engine is the scale-out design for models that do
not fit one chip: the mesh is (worker, pipe, model), each *logical worker*
owns a (pipe x model) submesh running its own pipelined + tensor/sequence/
expert-parallel replica (models/transformer.py), and robust aggregation runs
directly on the *sharded* gradients:

1.  ``loss_fn`` (built for shard_map, e.g. ``make_pipeline_loss``) computes
    each worker group's loss with collectives over (pipe, model) only; grads
    arrive naturally sharded: stage dim over ``pipe``, MLP/expert weights
    over ``model``.
2.  Gradients of *replicated* leaves are completed with a psum over exactly
    the in-group axes the leaf does not shard (its PartitionSpec says which).
3.  Per-worker perturbations (attack / lossy link) apply to the worker's own
    local shard — the same honest threat model as the flat engine, just
    expressed per-shard (a Byzantine worker corrupts all of its shards).
4.  **Per-bucket robust aggregation**: for every parameter leaf (split per
    layer when the leaf carries the scanned layer dim), one
    ``all_gather`` over the ``worker`` axis yields the (n, d_bucket) row
    matrix *for this shard only* — the full (n, d) matrix never exists
    anywhere. Distance-based rules complete their (n, n) matrix with a psum
    over ``model`` when the leaf's coordinates are sharded there. This is
    per-layer Krum/Bulyan (BASELINE.md config 5) by construction.
5.  With ``granularity='global'`` the per-leaf partial distances are instead
    accumulated (scaled by 1/replication so the psum is exact) into one
    global (n, n) matrix — the reference's whole-vector selection semantics
    (graph.py:144-168 flattens everything into a single vector) at sharded
    memory cost.
6.  The aggregated shard is already laid out like the parameter, so the
    optax update is local; worker-axis determinism (identical all_gather
    results) keeps every worker group's parameters bit-identical — the PS
    invariant, shard by shard.
"""

import math

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import config
from ..core.train_state import TrainState
from ..gars.common import centered_gram_sq_distances
from ..obs import trace
from ..utils import UserException
from ..utils import compat
from .mesh import model_axis, pipe_axis, worker_axis

_IN_GROUP_AXES = (pipe_axis, model_axis)


def _is_spec(x):
    return x is None or isinstance(x, P)


def _spec_axis_names(spec):
    names = set()
    for entry in spec or ():
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            names.update(entry)
        else:
            names.add(entry)
    return names


def _replication_axes(spec):
    """In-group mesh axes over which a leaf with this spec is replicated."""
    names = _spec_axis_names(spec)
    return tuple(a for a in _IN_GROUP_AXES if a not in names)


class ShardedRobustEngine:
    """Robust Byzantine-DP over logical workers that each span a submesh."""

    def __init__(self, mesh, gar, nb_real_byz=0, attack=None, lossy_link=None, granularity="layer",
                 exchange_dtype=None, worker_momentum=None, worker_metrics=False,
                 reputation_decay=None, quarantine_threshold=0.0,
                 l1_regularize=None, l2_regularize=None, chaos=None,
                 health_probe=True, nb_workers=None, secure=False, flight=None):
        self.mesh = mesh
        self.gar = gar
        # Logical workers decoupled from mesh slots (the flat engine's
        # discipline, brought here for the large-n regime): ``nb_workers``
        # may exceed the worker mesh axis, in which case each worker-group
        # submesh hosts k = n/W logical workers — their grads are vmapped,
        # their leading batch/buffer dims block-shard over the axis, and the
        # per-bucket all_gathers recover the full (n, ...) row matrices.
        # Default (None) keeps the historical one-worker-per-slot layout.
        self.nb_mesh_workers = mesh.shape[worker_axis]
        self.nb_workers = (
            int(nb_workers) if nb_workers is not None else self.nb_mesh_workers
        )
        if self.nb_workers % self.nb_mesh_workers != 0:
            raise UserException(
                "nb_workers (%d) must be a multiple of the worker mesh axis (%d)"
                % (self.nb_workers, self.nb_mesh_workers)
            )
        self.workers_per_device = self.nb_workers // self.nb_mesh_workers
        self._state_shardings = None  # captured by init_state, for put_state
        self._assemble_cache = {}  # slice-concat executables, per slice count
        self.nb_real_byz = int(nb_real_byz)
        self.attack = attack
        self.lossy_link = lossy_link
        # Time-varying fault regimes (chaos/schedule.py), the flat engine's
        # semantics: regime knobs switch on the traced step, stragglers'
        # lateness is drawn ONCE per (step, worker) so a late worker is late
        # for ALL of its shards (a whole logical worker misses the deadline,
        # not one of its tensors).
        from .engine import validate_chaos_args

        self.chaos = validate_chaos_args(chaos, attack, lossy_link, self.nb_workers, self.nb_real_byz)
        # Wire precision of the per-bucket worker-axis all_gathers (the
        # engine's dominant collective): bf16 halves the bytes; GAR math
        # stays float32 on upcast rows (see parallel/engine.py for the
        # identical policy on the flat engine).  float32 normalizes to None.
        dt = jnp.dtype(exchange_dtype) if exchange_dtype else None
        self.exchange_dtype = None if dt == jnp.float32 else dt
        # History-aware robustness (Karimireddy et al. 2021), same policy as
        # the flat engine: workers send bias-corrected momenta.  The buffer
        # is a per-worker pytree shaped like the params with a leading
        # worker dim, sharded P(worker, *param_spec).
        self.worker_momentum = None if worker_momentum is None else float(worker_momentum)
        if self.worker_momentum is not None and not 0.0 < self.worker_momentum < 1.0:
            raise UserException("worker_momentum must lie in (0, 1), got %r" % worker_momentum)
        # CLEVER stale infill carries the previously-sent values per leaf
        # (the reference's >1 MB UDP threshold is per-tensor too,
        # mpi_rendezvous_mgr.patch:507-513); buffer layout mirrors momentum.
        # Stale-mode chaos stragglers ride the same carry.
        self.carries_gradients = (lossy_link is not None and lossy_link.clever) or (
            self.chaos is not None and self.chaos.needs_carry
        )
        # Opt-in per-worker suspicion diagnostics, the flat engine's
        # worker_metrics: whole-model squared distance to the aggregate and
        # the mean per-bucket participation (see parallel/engine.py).
        self.worker_metrics = bool(worker_metrics)
        # In-step health probe (guardian/probe.py), the flat engine's
        # semantics: nested under metrics["probe"], zero extra compiles.
        self.health_probe = bool(health_probe)
        # Reputation EMA + quarantine, the flat engine's semantics
        # (parallel/engine.py): rank signal on the post-attack raw rows'
        # whole-model distance to the aggregate; up to f below-threshold
        # workers' rows masked NaN per bucket.
        from .engine import validate_reputation_args

        self.reputation_decay, self.quarantine_threshold = validate_reputation_args(
            gar, reputation_decay, quarantine_threshold
        )
        if granularity not in ("layer", "leaf", "global"):
            raise UserException("granularity must be layer, leaf or global (got %r)" % (granularity,))
        if granularity == "global" and (gar.uses_axis or gar.uses_key) and not gar.needs_distances:
            # The global path concatenates DISTANCES across leaves; iterative
            # rules would need their per-iteration row norms accumulated
            # across every leaf instead, which the per-leaf loop cannot do —
            # refuse rather than silently degrade to per-leaf semantics.
            raise UserException(
                "granularity:global is not supported for %s (whole-vector norms "
                "across leaves are not implemented); use granularity:layer"
                % type(gar).__name__
            )
        self.granularity = granularity
        if gar.nb_workers != self.nb_workers:
            raise UserException(
                "GAR was built for n=%d but the mesh worker axis is %d" % (gar.nb_workers, self.nb_workers)
            )
        if self.nb_real_byz > self.nb_workers:
            raise UserException("More real Byzantine workers than workers")
        if attack is not None and self.nb_real_byz == 0:
            raise UserException("An attack needs nb_real_byz > 0 to have anyone to run it")
        # l1/l2 regularization (reference: graph.py:125-139).  The flat
        # engine wraps the per-worker loss; under shard_map the loss is a
        # LOCAL PARTIAL, so a parameter-norm term in the loss would be
        # counted once per replicating device.  The reg term is separable
        # from the data term, so the engine instead applies its gradient
        # ANALYTICALLY (l1*sign(p) + 2*l2*p, elementwise on each shard) to
        # the psum-completed gradients — exact, shard-local, no double
        # counting — and adds the correctly replication-scaled norm to the
        # reported loss.
        self.l1_regularize = float(l1_regularize) if l1_regularize else None
        self.l2_regularize = float(l2_regularize) if l2_regularize else None
        # Authenticated submission (secure/submit.py), the flat engine's
        # semantics on sharded leaves: per-worker digests accumulate over
        # every leaf shard (mod-2^32 lane sums, psum-completed within the
        # worker group), chaos forge/tamper corrupt whole logical workers,
        # and rejected submissions NaN every leaf of that worker.
        self.secure = bool(secure)
        # Flight recorder (obs/flight.py), the flat engine's semantics: the
        # per-step ring is a replicated TrainState side buffer written at
        # the end of the step body — every recorded value is already
        # replicated (psum/all_gather-completed), so the write keeps
        # replication and the compile count equals the recorder-off run.
        self.flight = flight
        if flight is not None:
            flight.validate_for(
                nb_workers=self.nb_workers, probe=self.health_probe,
                worker_metrics=self.worker_metrics,
                chaos=self.chaos is not None, secure=self.secure,
            )

    # ------------------------------------------------------------------ #

    def init_state(self, init_fn, specs, tx, seed=0):
        """Create the sharded TrainState.

        Args:
          init_fn: key -> global parameter pytree (e.g. transformer.init_params).
          specs:   matching pytree of PartitionSpecs (transformer.param_specs).
          tx:      optax GradientTransformation.
        """
        shardings = jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs, is_leaf=_is_spec)
        params = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(seed))
        rep = NamedSharding(self.mesh, P())
        # Optimizer state must come out with EXPLICIT NamedShardings: optax
        # buffers that mirror the params (adam's mu/nu, momentum's trace —
        # they share the params' treedef) take the params' layouts, every
        # other allocation (schedule counts etc.) replicates.  Relying on
        # ambient-mesh propagation instead is version-fragile: on older JAX
        # there is no ambient mesh and jit commits fresh outputs to a single
        # device, which the spec-deriving build_step cannot consume.
        opt_shapes = jax.eval_shape(tx.init, params)
        params_treedef = jax.tree_util.tree_structure(params)
        param_shardings = jax.tree.map(lambda p: p.sharding, params)

        def params_like(node):
            try:
                return jax.tree_util.tree_structure(node) == params_treedef
            except TypeError:
                return False

        if params_treedef.num_leaves == 1:
            # a single-leaf treedef would "match" every leaf, so identify
            # the params-mirroring buffers by shape/dtype identity instead
            only = jax.tree_util.tree_leaves(params)[0]
            opt_shardings = jax.tree.map(
                lambda s: only.sharding
                if (s.shape, s.dtype) == (only.shape, only.dtype) else rep,
                opt_shapes,
            )
        else:
            opt_shardings = jax.tree.map(
                lambda node: param_shardings if params_like(node) else rep,
                opt_shapes, is_leaf=params_like,
            )
        with compat.set_mesh(self.mesh):  # new-JAX path also wants the mesh ambient
            opt_state = jax.jit(tx.init, out_shardings=opt_shardings)(params)

        def per_worker_zeros():
            m_shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, P(worker_axis, *tuple(s))),
                specs, is_leaf=_is_spec,
            )
            return jax.jit(
                lambda: jax.tree.map(
                    lambda p: jnp.zeros((self.nb_workers,) + p.shape, jnp.float32), params
                ),
                out_shardings=m_shardings,
            )()

        momentum = momentum_steps = carry = reputation = loss_ema = None
        flight = None
        if self.worker_momentum is not None:
            momentum = per_worker_zeros()
            momentum_steps = jax.device_put(jnp.zeros((), jnp.int32), rep)
        if self.carries_gradients:
            carry = per_worker_zeros()
        if self.reputation_decay is not None:
            reputation = jax.device_put(jnp.ones((self.nb_workers,), jnp.float32), rep)
        if self.health_probe:
            from ..guardian.probe import EMA_UNSET

            loss_ema = jax.device_put(jnp.float32(EMA_UNSET), rep)
        if self.flight is not None:
            # empty replicated ring, every slot tagged invalid (step -1)
            flight = jax.device_put(self.flight.init_buffers(), rep)
        state = TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), rep),
            params=params,
            opt_state=opt_state,
            rng=jax.device_put(jax.random.PRNGKey(seed), rep),
            carry=carry,
            momentum=momentum,
            momentum_steps=momentum_steps,
            reputation=reputation,
            loss_ema=loss_ema,
            flight=flight,
        )
        # Remember the layout for put_state (checkpoint restore re-sharding).
        self._state_shardings = jax.tree.map(lambda a: a.sharding, state)
        return state

    def shard_batch(self, batch):
        """Device_put a worker-major batch pytree (leading dim = nb_workers)."""
        return jax.device_put(batch, NamedSharding(self.mesh, P(worker_axis)))

    def shard_batches(self, batches):
        """Device_put a (K, nb_workers, ...) chunk for ``build_multi_step``.
        The step axis is unsharded, so chunk SLICES place identically — the
        input pipeline issues one transfer per slice (ChunkPipeline)."""
        return jax.device_put(batches, NamedSharding(self.mesh, P(None, worker_axis)))

    def assemble_batches(self, parts):
        """Concatenate step-axis chunk slices into one (K, nb_workers, ...)
        device chunk — the sharded-engine twin of
        ``RobustEngine.assemble_batches`` (jitted once per slice count;
        output is a fresh buffer, releasing the pipeline's host ping-pong
        buffers for reuse)."""
        fn = self._assemble_cache.get(len(parts))
        if fn is None:
            fn = jax.jit(lambda *xs: jax.tree.map(
                lambda *leaves: jnp.concatenate(leaves, axis=0), *xs))
            self._assemble_cache[len(parts)] = fn
        return fn(*parts)

    def put_state(self, state):
        """Re-shard a (possibly host-resident) state onto this mesh with the
        layout ``init_state`` established — the checkpoint-restore path
        (cli/runner.py) round-trips state through the host and needs the
        sharded placement back.  Leaves that are already live device arrays
        with the right sharding pass through unchanged."""
        if self._state_shardings is None:
            raise RuntimeError("put_state needs init_state to have run first")
        return jax.tree.map(jax.device_put, state, self._state_shardings)

    # ------------------------------------------------------------------ #

    def _perturb(self, g, spec, key, widx, previous=None, ridx=None, late=None):
        """Worker-local attack + lossy link + chaos regime on this worker's
        own shard.

        Returns (perturbed leaf, post-transport leaf) — the latter is what
        "the receiver saw", the stale value a lost packet keeps under CLEVER
        and a stale-mode straggler keeps re-submitting.  ``late`` is the
        worker's per-STEP lateness flag (drawn once in the body, shared by
        every leaf: a late worker misses the deadline for its whole
        gradient).
        """
        flat = g.reshape(-1)
        prev_flat = previous.reshape(-1) if previous is not None else None
        if self.attack is not None and not self.attack.omniscient:
            forged = self.attack.apply_local(flat, jax.random.fold_in(key, 1))
            flat = jnp.where(widx < self.nb_real_byz, forged, flat)
        if self.chaos is not None and self.chaos.has_local_attacks:
            forged = self.chaos.apply_local_attacks(ridx, flat, jax.random.fold_in(key, 1))
            flat = jnp.where(widx < self.nb_real_byz, forged, flat)
        if self.lossy_link is not None:
            flat = self.lossy_link.apply(flat, jax.random.fold_in(key, 2), widx, previous=prev_flat)
        if self.chaos is not None:
            if self.chaos.has_drop:
                flat = self.chaos.link.apply(
                    flat, jax.random.fold_in(key, 2), widx,
                    drop_rate=self.chaos.drop_rate(ridx),
                )
            if late is not None:
                flat = self.chaos.stragglers.apply(
                    flat, late, self.chaos.straggler_stale(ridx), previous=prev_flat
                )
        out = flat.reshape(g.shape)
        return out, out

    def _submission_pipeline(self, g_leaves, key, gidx, ridx):
        """The submission-forgery pipeline on sharded leaves (the flat
        engine's ``_perturb_local`` tail, see parallel/engine.py): chaos
        ``forge`` replaces every leaf of a coalition worker with impostor
        noise, sender digests accumulate over all leaf shards, ``tamper``
        flips a bit after signing, receiver digests follow, and under
        ``secure`` a rejected worker's every leaf reads NaN.

        Returns ``(g_leaves, secure_local)`` — ``secure_local`` (None unless
        ``secure``) holds the per-LOCAL-worker digests (lane sums over this
        device's shards; the body psum-completes them within the worker
        group) and the forge/reject verdicts.
        """
        from ..secure.submit import (
            DIGEST_LANES,
            FORGE_SCALE,
            row_digest,
            tamper_row,
        )

        chaos_forgery = self.chaos is not None and self.chaos.has_forgery
        if not (self.secure or chaos_forgery):
            return g_leaves, None
        k = self.workers_per_device
        out_leaves = [[] for _ in g_leaves]
        sent = jnp.zeros((k, DIGEST_LANES), jnp.uint32)
        recv = jnp.zeros((k, DIGEST_LANES), jnp.uint32)
        forged_flags, rejected_flags = [], []
        for j in range(k):
            widx = gidx * k + j
            # the 32_000+ offset namespace keeps these per-worker streams
            # disjoint from the per-(worker, leaf) perturbation parents and
            # the 30_000+ straggler draws (see the body's key discipline)
            wkey = jax.random.fold_in(key, 32_000 + widx)
            is_forge = is_tamper = None
            if chaos_forgery:
                fkey = jax.random.fold_in(wkey, 5)
                is_forge = (widx < self.nb_real_byz) & jax.random.bernoulli(
                    fkey, self.chaos.forge_rate(ridx)
                )
                tkey = jax.random.fold_in(wkey, 6)
                is_tamper = (widx < self.nb_real_byz) & jax.random.bernoulli(
                    tkey, self.chaos.tamper_rate(ridx)
                )
            forged_flag = is_forge if is_forge is not None else jnp.bool_(False)
            rejected = forged_flag
            if is_tamper is not None:
                rejected = rejected | is_tamper
            sent_j = jnp.zeros((DIGEST_LANES,), jnp.uint32)
            recv_j = jnp.zeros((DIGEST_LANES,), jnp.uint32)
            for i, g in enumerate(g_leaves):
                flat = g[j].reshape(-1).astype(jnp.float32)
                if is_forge is not None:
                    impostor = jax.random.normal(
                        jax.random.fold_in(jax.random.fold_in(fkey, 1), i),
                        flat.shape, flat.dtype,
                    ) * jnp.float32(FORGE_SCALE)
                    flat = jnp.where(is_forge, impostor, flat)
                leaf_digest = None
                if self.secure:
                    # per-leaf salt: leaves must not alias in the checksum
                    leaf_digest = row_digest(flat, salt=i * 0x9E3779B1)
                    sent_j = sent_j + leaf_digest
                if is_tamper is not None and i == 0:
                    # one bit flipped in transit (the first leaf's shard)
                    flat = jnp.where(
                        is_tamper, tamper_row(flat, jax.random.fold_in(tkey, 1)), flat
                    )
                if self.secure:
                    # no in-transit transform on this leaf -> received bytes
                    # are the submitted bytes, reuse the checksum
                    if chaos_forgery and i == 0:
                        leaf_digest = row_digest(flat, salt=i * 0x9E3779B1)
                    recv_j = recv_j + leaf_digest
                    flat = jnp.where(rejected, jnp.nan, flat)
                out_leaves[i].append(flat.reshape(g[j].shape).astype(g.dtype))
            sent = sent.at[j].set(sent_j)
            recv = recv.at[j].set(recv_j)
            forged_flags.append(forged_flag)
            rejected_flags.append(rejected)
        g_leaves = [jnp.stack(rows) for rows in out_leaves]
        if not self.secure:
            return g_leaves, None
        return g_leaves, {
            "digest_sent": sent,
            "digest_recv": recv,
            "forged": jnp.stack(forged_flags),
            "rejected": jnp.stack(rejected_flags),
        }

    def _leaf_buckets(self, g, spec):
        """Reshape a locally worker-stacked (k, ...) leaf to (k, n_buckets,
        d_bucket) rows-to-be."""
        k = g.shape[0]
        if self.granularity == "layer" and spec is not None and len(spec) >= 2 and spec[0] == pipe_axis:
            # Stage-stacked leaf (local stage dim 1, then the scanned layer
            # dim): one bucket per layer.
            return g.reshape(k, g.shape[1] * g.shape[2], -1)
        return g.reshape(k, 1, -1)

    def _gather_rows(self, buckets):
        """(k, Lb, d) local buckets -> (Lb, n, d) per-worker rows via one
        all_gather over the worker axis (worker-major: global worker index
        is group * k + local slot, the same layout the flat engine uses)."""
        if self.exchange_dtype is not None:
            buckets = buckets.astype(self.exchange_dtype)
        rows = jax.lax.all_gather(buckets, worker_axis)  # (W, k, Lb, d)
        if self.exchange_dtype is not None:
            rows = rows.astype(jnp.float32)
        rows = rows.reshape((self.nb_workers,) + rows.shape[2:])  # (n, Lb, d)
        return jnp.swapaxes(rows, 0, 1)

    def _apply_omniscient(self, rows, key, ridx=None):
        byz_mask = jnp.arange(self.nb_workers) < self.nb_real_byz
        forged = False
        if self.attack is not None and self.attack.omniscient:
            rows = jax.vmap(lambda m: self.attack.apply_matrix(m, byz_mask, key))(rows)
            forged = True
        if self.chaos is not None and self.chaos.has_omniscient_attacks:
            rows = jax.vmap(
                lambda m: self.chaos.apply_omniscient_attacks(ridx, m, byz_mask, key)
            )(rows)
            forged = True
        if forged and self.exchange_dtype is not None:
            # forged rows crossed the same quantized wire as honest ones
            rows = rows.astype(self.exchange_dtype).astype(jnp.float32)
        return rows

    def _bucket_distances(self, rows, spec):
        """(Lb, n, n) squared distances for this leaf's buckets (exact)."""
        partial = jax.vmap(centered_gram_sq_distances)(rows.astype(jnp.float32))
        if model_axis in _spec_axis_names(spec):
            partial = jax.lax.psum(partial, model_axis)
        return jnp.maximum(partial, 0.0)

    def _replication_scale(self, spec):
        scale = 1.0
        for a in _replication_axes(spec):
            scale /= self.mesh.shape[a]
        return scale

    # ------------------------------------------------------------------ #

    def _make_body(self, loss_fn, tx, state_specs):
        """The single-step shard_map body, shared by ``build_step`` and
        ``build_multi_step`` (the scan over it)."""
        param_specs = state_specs.params
        gar = self.gar
        k = self.workers_per_device

        def body(state, batch):
            key = jax.random.fold_in(state.rng, state.step)
            gidx = jax.lax.axis_index(worker_axis)  # worker-GROUP index
            # Active chaos regime + per-STEP worker lateness (one draw per
            # logical worker, shared by all its leaves).  The lateness key
            # lives in the 30_000+ offset namespace — fold_in(key, widx) is
            # the PARENT of every per-leaf stream (fold i, then tags 1/2),
            # so folding the straggler tag onto it directly would collide
            # with leaf index 5's stream (same convention as the 10_000+i /
            # 20_000+i offsets the engines use elsewhere).
            ridx = None
            lates = [None] * k
            if self.chaos is not None:
                ridx = self.chaos.regime_index(state.step)
                if self.chaos.has_stragglers:
                    lates = [
                        self.chaos.stragglers.is_late(
                            jax.random.fold_in(key, 30_000 + gidx * k + j),
                            gidx * k + j,
                            self.chaos.straggler_rate(ridx),
                        )
                        for j in range(k)
                    ]
            if k == 1:
                # one logical worker per submesh: the historical (and
                # bit-proven) unvmapped path — keep it byte-for-byte
                local = jax.tree.map(lambda x: x[0], batch)  # strip block dim
                loss, grads = jax.value_and_grad(loss_fn)(state.params, local)
                losses = loss[None]
                grads = jax.tree.map(lambda g: g[None], grads)
            else:
                # k logical workers per submesh (the large-n regime): vmap
                # the per-worker loss/grad — every leaf leads with k
                losses, grads = jax.vmap(
                    lambda b: jax.value_and_grad(loss_fn)(state.params, b)
                )(batch)

            g_leaves, treedef = jax.tree_util.tree_flatten(grads)
            s_leaves = treedef.flatten_up_to(param_specs)

            # (2) complete replicated-leaf grads within the worker group
            g_leaves = [
                jax.lax.psum(g, _replication_axes(s)) if _replication_axes(s) else g
                for g, s in zip(g_leaves, s_leaves)
            ]
            # (2a) l1/l2 regularization, analytically on the completed grads
            # (see __init__): part of every worker's HONEST gradient, so it
            # lands before momentum and before the Byzantine perturbation —
            # the flat engine's in-loss placement, same math.
            l1, l2 = self.l1_regularize, self.l2_regularize
            if l1 or l2:
                p_leaves = jax.tree_util.tree_leaves(state.params)
                reg = jnp.float32(0.0)
                for i, (p, s) in enumerate(zip(p_leaves, s_leaves)):
                    p32 = p.astype(jnp.float32)
                    delta = jnp.zeros_like(p32)
                    if l1:
                        delta = delta + l1 * jnp.sign(p32)
                        reg = reg + l1 * jnp.sum(jnp.abs(p32)) * self._replication_scale(s)
                    if l2:
                        delta = delta + 2.0 * l2 * p32
                        reg = reg + l2 * jnp.sum(p32 * p32) * self._replication_scale(s)
                    g_leaves[i] = g_leaves[i] + delta.astype(g_leaves[i].dtype)
                # scaled per-leaf partials psum exactly like the data loss:
                # the in-group psum in `metrics` then counts the norm once
                # (every logical worker's loss carries the reg term, the flat
                # engine's per-worker in-loss placement)
                losses = losses + reg
            # (2b) honest worker momentum (pre-attack, like the flat engine):
            # send bias-corrected momenta, carry the uncorrected buffer
            new_momentum, new_momentum_steps = state.momentum, state.momentum_steps
            if self.worker_momentum is not None:
                beta = self.worker_momentum
                # momentum buffers are worker-sharded: local block (k, ...)
                m_leaves, _ = jax.tree_util.tree_flatten(state.momentum)
                new_momentum_steps = state.momentum_steps + 1
                corr = 1.0 - beta ** new_momentum_steps.astype(jnp.float32)
                m_new = [beta * m + (1.0 - beta) * g for m, g in zip(m_leaves, g_leaves)]
                g_leaves = [m / corr for m in m_new]
                new_momentum = jax.tree_util.tree_unflatten(treedef, m_new)
            # (3) per-worker perturbation of each logical worker's own shards
            # (skipped entirely when no adversity is configured — at k
            # workers per submesh the k-fold loop would otherwise pay trace
            # size for an identity transform)
            carry_leaves = None
            if self.carries_gradients:
                carry_leaves = jax.tree_util.tree_leaves(state.carry)  # (k, ...)
            new_carry = state.carry
            if (self.attack is not None or self.lossy_link is not None
                    or self.chaos is not None):
                post_leaves = []
                for i, (g, s) in enumerate(zip(g_leaves, s_leaves)):
                    outs, posts = [], []
                    for j in range(k):
                        widx = gidx * k + j
                        out, post = self._perturb(
                            g[j], s,
                            jax.random.fold_in(jax.random.fold_in(key, widx), i),
                            widx,
                            previous=(
                                carry_leaves[i][j]
                                if carry_leaves is not None else None
                            ),
                            ridx=ridx, late=lates[j],
                        )
                        outs.append(out)
                        posts.append(post)
                    g_leaves[i] = jnp.stack(outs)
                    post_leaves.append(jnp.stack(posts))
                if self.carries_gradients:
                    new_carry = jax.tree_util.tree_unflatten(treedef, post_leaves)

            # (3b) submission forgery + authentication digests (secure/):
            # impersonated/tampered submissions, sender/receiver checksums
            # over every leaf shard, reject-to-NaN under ``secure``
            g_leaves, secure_local = self._submission_pipeline(
                g_leaves, key, gidx, ridx
            )

            # (4/5) per-bucket robust aggregation over the worker axis
            all_rows = []
            for i, (g, s) in enumerate(zip(g_leaves, s_leaves)):
                rows = self._gather_rows(self._leaf_buckets(g, s))
                rows = self._apply_omniscient(rows, jax.random.fold_in(key, 10_000 + i), ridx=ridx)
                all_rows.append(rows)

            # Quarantine BEFORE any distance computation (incl. the global
            # path below): masked rows must read +inf-distant to selection
            # rules, never finite-distant-but-NaN-valued.  raw rows are kept
            # for the reputation signal.
            raw_all_rows = all_rows
            if self.quarantine_threshold:
                from .engine import quarantine_mask

                qmask = quarantine_mask(
                    state.reputation, self.quarantine_threshold, gar.nb_byz_workers
                )
                all_rows = [
                    jnp.where(qmask[None, :, None], jnp.nan, rows) for rows in all_rows
                ]

            global_dist2 = None
            if self.granularity == "global" and gar.needs_distances:
                acc = jnp.zeros((self.nb_workers, self.nb_workers), jnp.float32)
                for rows, s in zip(all_rows, s_leaves):
                    partial = centered_gram_sq_distances(
                        rows.reshape(self.nb_workers, -1).astype(jnp.float32)
                    )
                    acc = acc + partial * self._replication_scale(s)
                global_dist2 = jnp.maximum(jax.lax.psum(acc, _IN_GROUP_AXES), 0.0)

            agg_leaves = []
            # Suspicion accumulators (worker_metrics): whole-model per-worker
            # squared distance to the aggregate — per-leaf partials scaled by
            # the replication factor exactly like grad_norm's, psum-completed
            # below — and the mean per-bucket participation.  Participation
            # values are identical on every in-group device EXCEPT along the
            # pipe axis of stage-stacked leaves (distinct buckets), so each
            # contribution is scaled by 1/(replicating axes' size) and the
            # in-group psum then counts every distinct bucket exactly once.
            wdist = jnp.zeros((self.nb_workers,), jnp.float32)
            part_sum = jnp.zeros((self.nb_workers,), jnp.float32)
            part_count = 0.0  # global distinct-bucket count (static)
            rep_dist = jnp.zeros((self.nb_workers,), jnp.float32)
            # (vmapped rule calls below: the Pallas auto-tier detects the
            # batching trace centrally and stays on jnp — gars/common.py
            # _is_batched_tracer)
            for rows, raw_rows, g, s in zip(all_rows, raw_all_rows, g_leaves, s_leaves):
                participation = None
                if gar.needs_distances:
                    if global_dist2 is not None:
                        dist2 = jnp.broadcast_to(global_dist2, rows.shape[:1] + global_dist2.shape)
                    else:
                        dist2 = self._bucket_distances(rows, s)
                    if self.worker_metrics:
                        # One pass: the memoized selection graph serves both
                        # the aggregate and the participation (two separate
                        # vmaps would trace it twice per leaf).
                        agg, participation = jax.vmap(
                            gar.aggregate_block_and_participation
                        )(rows, dist2)
                    else:
                        agg = jax.vmap(gar.aggregate_block)(rows, dist2)
                elif gar.uses_axis or gar.uses_key:
                    # Iterative rules' row norms complete over the model axis
                    # when this leaf's dimensions are sharded across it —
                    # exactly _bucket_distances' discipline — so every shard
                    # derives identical weights and the result matches dense.
                    # Randomized meta-rules get the replicated step key (one
                    # permutation per step, same on every device and leaf).
                    axis = model_axis if model_axis in _spec_axis_names(s) else None
                    from ..gars import GAR_KEY_TAG

                    gkey = jax.random.fold_in(key, GAR_KEY_TAG)
                    if self.worker_metrics:
                        agg, participation = jax.vmap(
                            lambda r, axis=axis: gar.aggregate_block_and_participation(
                                r, None, axis_name=axis, key=gkey
                            )
                        )(rows)
                    else:
                        agg = jax.vmap(
                            lambda r, axis=axis: gar._call_aggregate(
                                r, None, axis_name=axis, key=gkey)
                        )(rows)
                else:
                    agg = jax.vmap(lambda r: gar.aggregate_block(r, None))(rows)
                if self.reputation_decay is not None:
                    rdiff = raw_rows.astype(jnp.float32) - agg.astype(jnp.float32)[:, None, :]
                    rep_dist = rep_dist + jnp.sum(rdiff * rdiff, axis=(0, 2)) * self._replication_scale(s)
                if self.worker_metrics:
                    diff = rows.astype(jnp.float32) - agg.astype(jnp.float32)[:, None, :]
                    wdist = wdist + jnp.sum(diff * diff, axis=(0, 2)) * self._replication_scale(s)
                    if participation is not None:
                        stacked = (
                            self.granularity == "layer" and s is not None
                            and len(s) >= 2 and s[0] == pipe_axis
                        )
                        rep = (model_axis,) + (() if stacked else (pipe_axis,))
                        pscale = 1.0
                        for a in rep:
                            pscale /= self.mesh.shape[a]
                        part_sum = part_sum + jnp.sum(participation, axis=0) * pscale
                        part_count += participation.shape[0] * (
                            self.mesh.shape[pipe_axis] if stacked else 1
                        )
                # one aggregate per PARAMETER: strip the local worker
                # stacking dim from the layout target
                agg_leaves.append(agg.reshape(g.shape[1:]).astype(g.dtype))
            agg_tree = jax.tree_util.tree_unflatten(treedef, agg_leaves)

            # (6) local optax update — layouts already match the parameters
            updates, opt_state = tx.update(agg_tree, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)

            sq = jnp.float32(0.0)
            for agg, s in zip(agg_leaves, s_leaves):
                sq = sq + jnp.sum(jnp.square(agg.astype(jnp.float32))) * self._replication_scale(s)
            grad_norm = jnp.sqrt(jax.lax.psum(sq, _IN_GROUP_AXES))

            new_reputation = state.reputation
            if self.reputation_decay is not None:
                from ..gars.common import nonfinite_to_inf, smallest_k_mask

                rdist = jax.lax.psum(rep_dist, _IN_GROUP_AXES)
                signal = smallest_k_mask(
                    nonfinite_to_inf(rdist),
                    self.nb_workers - gar.nb_byz_workers,
                ).astype(jnp.float32) * jnp.isfinite(rdist).astype(jnp.float32)
                beta = self.reputation_decay
                new_reputation = beta * state.reputation + (1.0 - beta) * signal

            # loss is a local partial: sum the local workers, then the worker
            # group's devices, then groups
            total_loss = jax.lax.psum(jnp.sum(losses), _IN_GROUP_AXES + (worker_axis,))
            new_loss_ema = state.loss_ema
            probe_fields = None
            if self.health_probe:
                from ..guardian import probe as health

                # Per-worker NaN-row flags over the POST-TRANSPORT shards:
                # count this worker's non-finite coordinates locally,
                # complete over the worker group, flag, gather workers.
                bad = jnp.zeros((k,), jnp.int32)
                for g in g_leaves:
                    bad = bad + jnp.sum(
                        (~jnp.isfinite(g)).astype(jnp.int32),
                        axis=tuple(range(1, g.ndim)),
                    )
                bad = jax.lax.psum(bad, _IN_GROUP_AXES)
                worker_nan = jax.lax.all_gather(bad > 0, worker_axis).reshape(
                    self.nb_workers
                )
                probe_fields = health.probe_metrics(
                    total_loss, grad_norm,
                    health.spike_score(total_loss, state.loss_ema), worker_nan,
                )
                new_loss_ema = health.update_loss_ema(state.loss_ema, total_loss)
            new_state = state.replace(step=state.step + 1, params=params, opt_state=opt_state,
                                      carry=new_carry, momentum=new_momentum,
                                      momentum_steps=new_momentum_steps,
                                      reputation=new_reputation, loss_ema=new_loss_ema)
            metrics = {
                "total_loss": total_loss,
                "grad_norm": grad_norm,
            }
            if probe_fields is not None:
                metrics[health.PROBE_KEY] = probe_fields
            if secure_local is not None:
                # complete each worker's lane sums over its in-group shards
                # (uint32 psum wraps mod 2^32 — the checksum's own domain),
                # then gather worker-major like the probe's NaN flags
                def complete(local, summed):
                    value = (
                        jax.lax.psum(local, _IN_GROUP_AXES) if summed else local
                    )
                    gathered = jax.lax.all_gather(value, worker_axis)
                    return gathered.reshape((self.nb_workers,) + value.shape[1:])

                metrics["secure"] = {
                    "digest_sent": complete(secure_local["digest_sent"], True),
                    "digest_recv": complete(secure_local["digest_recv"], True),
                    "forged": complete(secure_local["forged"], False),
                    "rejected": complete(secure_local["rejected"], False),
                }
            if ridx is not None:
                metrics["chaos_regime"] = ridx  # replicated function of step
            if self.worker_metrics:
                metrics["worker_sq_dist"] = jax.lax.psum(wdist, _IN_GROUP_AXES)
                if part_count:
                    metrics["worker_participation"] = (
                        jax.lax.psum(part_sum, _IN_GROUP_AXES) / part_count
                    )
                if self.reputation_decay is not None:
                    metrics["worker_reputation"] = new_reputation
                    if self.quarantine_threshold:
                        from .engine import quarantine_mask as _qmask

                        metrics["nb_quarantined"] = jnp.sum(
                            _qmask(
                                state.reputation, self.quarantine_threshold,
                                gar.nb_byz_workers,
                            ).astype(jnp.int32)
                        )
            if self.flight is not None:
                # In-scan flight-recorder write (obs/flight.py): each lane
                # stores the exact traced value the metrics dict carries,
                # so ring rows are bit-identical to per-step metrics by
                # construction.
                new_state = new_state.replace(
                    flight=self.flight.record(state.flight, state.step, metrics)
                )
            return new_state, metrics

        return body

    def build_step(self, loss_fn, tx, state):
        """Build the jitted sharded robust training step.

        Args:
          loss_fn: (params_local, worker_batch) -> scalar *local partial*
            loss, written for shard_map (collectives over pipe/model
            allowed); the sum over the worker group's devices must equal the
            worker's batch loss (see models/transformer.make_pipeline_loss —
            in-loss final psums would corrupt the gradients).
          tx:      optax GradientTransformation.
          state:   the TrainState from ``init_state`` (used for its layout).
        Returns:
          step(state, batch) -> (state, metrics); ``batch`` leaves lead with
          the worker dim.
        """
        state_specs = jax.tree.map(lambda a: a.sharding.spec, state)
        body = self._make_body(loss_fn, tx, state_specs)
        sharded = compat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(state_specs, P(worker_axis)),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
        # Host-side span wrapper only (obs/trace.py): the jit underneath is
        # untouched — zero added compiles, ``_cache_size`` falls through.
        # EXPLICIT out_shardings pin the output state to the init_state
        # layout: without them the compiler canonicalizes size-1 mesh axes
        # to replicated specs, so the SECOND step call would see differently
        # committed inputs and retrace (the zero-steady-state-recompile bar,
        # tests/test_gar_scaling.py).
        out_shardings = (
            jax.tree.map(lambda a: a.sharding, state),
            NamedSharding(self.mesh, P()),
        )
        return trace.traced(
            "train_step.dispatch",
            jax.jit(sharded, donate_argnums=(0,), out_shardings=out_shardings),
            cat="train",
        )

    def build_multi_step(self, loss_fn, tx, state, repeat_steps=None):
        """K-step trainer in one dispatch: ``lax.scan`` over the step body,
        mirroring the flat engine's ``build_multi_step`` (which removes the
        per-step host dispatch the reference pays as a PS round-trip per
        ``sess.run``, runner.py:562-576).

        Two forms, like the flat engine:
        - ``repeat_steps=None``: ``multi(state, batches)`` with every batch
          leaf leading (K, nb_workers, ...) — K distinct batches.
        - ``repeat_steps=K``: ``multi(state, batch)`` reuses one resident
          worker-major batch for K steps (throughput benches).
        Metrics come back per step (leading K)."""
        state_specs = jax.tree.map(lambda a: a.sharding.spec, state)
        body = self._make_body(loss_fn, tx, state_specs)

        if repeat_steps is None:

            def many(state, batches):
                return jax.lax.scan(body, state, batches)

            batch_spec = P(None, worker_axis)
        else:

            def many(state, batch):
                return jax.lax.scan(
                    lambda s, _: body(s, batch), state, None, length=int(repeat_steps)
                )

            batch_spec = P(worker_axis)

        sharded = compat.shard_map(
            many,
            mesh=self.mesh,
            in_specs=(state_specs, batch_spec),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
        # Same out_shardings discipline as build_step: keep the output state
        # committed exactly like init_state's, or call 2 retraces.
        out_shardings = (
            jax.tree.map(lambda a: a.sharding, state),
            NamedSharding(self.mesh, P()),
        )
        return trace.traced(
            "train_multi_step.dispatch",
            jax.jit(sharded, donate_argnums=(0,), out_shardings=out_shardings),
            cat="train",
        )

    def build_gar_probe(self, d, seed=0):
        """Jitted GAR-only executable at (n, d) — the sharded twin of
        ``RobustEngine.build_gar_probe`` (the measurement instrument behind
        ``gar_seconds_total`` / the ``gar.aggregate`` span).

        The engine proper reduces per leaf/bucket; the probe measures ONE
        rule application over the whole-model (n, d) row matrix on a single
        replica — exact for ``granularity=global`` (one selection over the
        flattened vector) and an upper bound for layer/leaf granularity
        (the same arithmetic split across buckets).  Attacks/quarantine are
        excluded: the probe times the rule, not the adversity simulation."""
        from ..gars import GAR_KEY_TAG
        from ..gars.common import centered_gram_sq_distances

        # Column-shard the synthetic rows over the worker axis (the flat
        # engine's probe layout): a replicated (n, d) matrix at whole-model
        # d and large n would cost n x the model footprint PER DEVICE — the
        # sharded engine's whole reason to exist is that that doesn't fit.
        # The body is plain jit, so GSPMD partitions the distance Gram and
        # the rule's columnwise work along d automatically.  d is padded to
        # the worker-axis multiple (sharding a dim requires divisibility;
        # model_dim is an arbitrary parameter count), and the rows are
        # generated ON DEVICE under jit with an explicit output sharding so
        # the host never materializes the (n, d) matrix.
        W = self.nb_mesh_workers
        blk = -(-int(d) // W)
        make_rows = jax.jit(
            lambda k: jax.random.normal(k, (self.nb_workers, W * blk), jnp.float32),
            out_shardings=NamedSharding(self.mesh, P(None, worker_axis)),
        )
        rows = make_rows(jax.random.PRNGKey(seed))
        gar = self.gar

        def body(rows, key):
            dist2 = None
            if gar.needs_distances:
                # jnp-tier Gram distances (same as _bucket_distances): the
                # common pairwise_sq_distances auto-dispatches to a Pallas
                # kernel on TPU, which GSPMD cannot partition over the
                # column-sharded rows
                dist2 = jnp.maximum(centered_gram_sq_distances(rows), 0.0)
            gar_key = jax.random.fold_in(key, GAR_KEY_TAG)
            return gar._call_aggregate(rows, dist2, axis_name=None, key=gar_key)

        fn = jax.jit(body)
        base = jax.random.PRNGKey(seed)

        def probe(step=0):
            return fn(rows, jax.random.fold_in(base, step))

        return probe

    def build_eval(self, loss_fn, state):
        """Jitted eval: mean of the sharded loss over the worker axis.

        Built once from ``state``'s layout (like ``build_step``) so repeated
        cadenced evals hit the jit cache instead of recompiling.
        """
        specs = jax.tree.map(lambda a: a.sharding.spec, state)
        k = self.workers_per_device

        def body(state, batch):
            if k == 1:
                local = jax.tree.map(lambda x: x[0], batch)
                total = loss_fn(state.params, local)  # local partial
            else:
                total = jnp.sum(
                    jax.vmap(lambda b: loss_fn(state.params, b))(batch)
                )
            return jax.lax.psum(total, _IN_GROUP_AXES + (worker_axis,)) / self.nb_workers

        sharded = compat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(specs, P(worker_axis)),
            out_specs=P(),
            check_vma=False,
        )
        return trace.traced("eval_step.dispatch", jax.jit(sharded), cat="eval")

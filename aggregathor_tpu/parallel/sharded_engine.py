"""Deprecation alias — the fully-sharded engine was folded into the one
sharding-polymorphic :class:`~aggregathor_tpu.parallel.engine.RobustEngine`
(``sharding="sharded"``; docs/engine.md).  This module remains only so
pre-unification imports keep resolving; new code should import from
``aggregathor_tpu.parallel.engine`` (or the package root) directly.
"""

from .engine import RobustEngine, ShardedRobustEngine  # noqa: F401

"""The robust SPMD training engine.

One jitted step function replaces the reference's entire per-step distributed
dance (worker gradient push over gRPC/MPI/UDP -> PS-side GAR -> variable
update, SURVEY.md §3.1).  Dataflow per step, for ``n`` logical workers over a
``W``-device ``worker`` mesh axis (k = n/W workers per device):

1.  **Isolated worker gradients** — the batch arrives worker-sharded; each
    device vmaps its k workers' forward/backward.  Gradients are flattened to
    (k, d) with the coherent pytree layout (core/flatten.py).
2.  **Local Byzantine attack / lossy link** — transforms that only read the
    worker's own slot run here, before any collective (honest threat model).
3.  **Reshard worker->dimension** — ``all_to_all`` turns the implicit (n, d)
    gradient matrix into per-device column blocks (n, d/W).  This is the
    engine's key memory move: no device ever holds n gradients, per-device
    footprint stays O(d) (SURVEY.md §7 hard part (b)).
4.  **Omniscient attacks** — coalition attacks needing honest statistics
    (coordinate-wise mean/std) apply blockwise on the gathered rows.
5.  **Distances** — Krum/Bulyan need the (n, n) squared-distance matrix: each
    device computes its block's partial Gram contribution, one O(n²) ``psum``
    completes it (vs the reference's O(n²·d) PS-side loop, op_krum/cpu.cpp).
6.  **Blockwise GAR** — every rule reduces its column block locally
    (selection weights are identical on all devices by construction).
7.  **Gather + update** — ``all_gather`` restores the aggregated (d,) vector;
    the optax update applies identically on every device, keeping parameters
    replicated — the PS's "one canonical copy" without a PS (train_state.py).

Wire cost: one all_to_all (d floats out/in per device) + one O(n²) psum + one
all_gather (d floats) ≈ 2x a ring allreduce — the minimum for robust
aggregation, since the GAR provably needs per-worker gradients, not their sum
(SURVEY.md §2.6).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
import optax

from .. import config
from ..core.flatten import FlatMap
from ..core.train_state import TrainState
from ..gars.common import centered_gram_sq_distances
from ..obs import trace
from ..utils import UserException
from ..utils import compat
from .mesh import worker_axis


def validate_reputation_args(gar, reputation_decay, quarantine_threshold):
    """Shared validation of the reputation/quarantine knobs (both engines).

    Returns the normalized ``(decay, threshold)`` pair.  Quarantine is
    bounded by the rule's declared budget: at most ``f`` workers are masked
    per step (``quarantine_mask``), so a NaN-excluding rule sized for f
    Byzantine rows never sees more dead rows than it tolerates — which is
    why ``f >= 1`` is required to quarantine at all."""
    decay = None if reputation_decay is None else float(reputation_decay)
    threshold = float(quarantine_threshold)
    if decay is not None and not 0.0 < decay < 1.0:
        raise UserException("reputation_decay must lie in (0, 1), got %r" % reputation_decay)
    if threshold:
        if decay is None:
            raise UserException("quarantine_threshold needs reputation_decay set")
        if not 0.0 < threshold < 1.0:
            raise UserException(
                "quarantine_threshold must lie in (0, 1), got %r" % quarantine_threshold
            )
        if gar.nb_byz_workers < 1:
            raise UserException(
                "Quarantine masks up to f workers per step; declare "
                "--nb-decl-byz-workers >= 1 to use it"
            )
        if not gar.nan_row_tolerant:
            from ..gars import gars as _registry

            tolerant = sorted(
                name for name in _registry.itemize()
                if getattr(_registry.get(name), "nan_row_tolerant", False)
            )
            # ``bucketing``/``hier`` set nan_row_tolerant per-INSTANCE (they
            # inherit their child rules' tolerance), so the class-attribute
            # scan above cannot list them — name them explicitly.
            raise UserException(
                "Quarantine masks rows to NaN, which %s does not cleanly "
                "exclude (pick a NaN-excluding rule: %s; or bucketing/hier "
                "with NaN-tolerant child rules)"
                % (type(gar).__name__, ", ".join(tolerant))
            )
    return decay, threshold


def validate_chaos_args(chaos, attack, lossy_link, nb_workers, nb_real_byz):
    """Shared validation of a ChaosSchedule against the engine's own
    configuration (both engines).  Returns ``chaos`` unchanged."""
    if chaos is None:
        return None
    if attack is not None or lossy_link is not None:
        raise UserException(
            "--chaos subsumes the static --attack/--UDP knobs: encode them as "
            "schedule regimes instead (e.g. '0:attack=empire' / '0:drop=0.3')"
        )
    if chaos.nb_workers != nb_workers:
        raise UserException(
            "ChaosSchedule was built for n=%d workers but the engine has %d"
            % (chaos.nb_workers, nb_workers)
        )
    if chaos.has_attacks or getattr(chaos, "has_forgery", False):
        if nb_real_byz == 0:
            raise UserException(
                "The chaos schedule declares attack/forge/tamper regimes; they "
                "need --nb-real-byz-workers > 0 to have anyone to run them"
            )
        if chaos.nb_real_byz != nb_real_byz:
            # the schedule sized its attacks (e.g. little's z formula) for a
            # different coalition than the engine will gate
            raise UserException(
                "ChaosSchedule was built for %d real Byzantine workers but "
                "the engine declares %d" % (chaos.nb_real_byz, nb_real_byz)
            )
    return chaos


def quarantine_mask(reputation, threshold, nb_byz):
    """(n,) bool: below-threshold AND among the ``nb_byz`` lowest
    reputations — the cap keeps the masked count within the NaN budget the
    rule's (n, f) sizing tolerates (an unbounded mask could exceed it when
    the rank signal rotates across honest stragglers)."""
    from ..gars.common import smallest_k_mask

    return (reputation < threshold) & smallest_k_mask(reputation, nb_byz)


def _partial_pairwise_sq_distances(block):
    """Per-block contribution to the (n, n) squared-distance matrix.

    Direct difference form on the (n, d_block) block would cost O(n²·d_block)
    memory, so the shared centered-Gram helper is used; psum across blocks
    then yields the same convention as the dense tier (NaN anywhere -> NaN
    entry; per-block median centering is a valid translation per block).

    On TPU, large blocks dispatch to the Pallas streaming distance kernel
    (ops/pallas_kernels.py): the Gram form's robust centering pass is a
    per-column median — the same order-statistic cost the Pallas tier
    removes from the coordinate rules (measured r4: krum dist+score at
    d=8.4M, 9.5 ms Pallas vs 398 ms jnp) — while the streamed difference
    form needs no centering because it never cancels.
    """
    block = block.astype(jnp.float32)
    from ..gars.common import use_pallas_coordinate_tier

    if use_pallas_coordinate_tier(block):
        from ..ops import pallas_kernels as pk

        return pk.pairwise_sq_distances(block)
    return centered_gram_sq_distances(block)


class RobustEngine:
    """Builds jitted robust train/eval steps over a (worker, model) mesh."""

    def __init__(self, mesh, gar, nb_workers, nb_real_byz=0, attack=None, lossy_link=None,
                 exchange_dtype=None, worker_momentum=None, batch_transform=None,
                 worker_metrics=False, reputation_decay=None, quarantine_threshold=0.0,
                 granularity="vector", leaf_bucketing="auto", trace_ops=False, chaos=None,
                 health_probe=True, secure=False, flight=None):
        self.mesh = mesh
        self.gar = gar
        self.nb_workers = int(nb_workers)
        self.nb_real_byz = int(nb_real_byz)
        self.attack = attack
        self.lossy_link = lossy_link
        # Time-varying fault regimes (chaos/schedule.py): the schedule's
        # regime index is computed from the TRACED step counter each step, so
        # attack/loss/straggler knobs switch inside the one compiled program.
        # Chaos SUBSUMES the static whole-run knobs — mixing both would give
        # two transport simulations with colliding PRNG streams.
        self.chaos = validate_chaos_args(chaos, attack, lossy_link, self.nb_workers, self.nb_real_byz)
        # Device-side augmentation: ``batch_transform(worker_batch, key) ->
        # worker_batch`` runs INSIDE the jitted step, per worker, train-only
        # (eval paths never apply it).  Keys are a function of (run seed,
        # step, global worker index) so worker w's augmentation stream is
        # independent of nb_workers/device placement — the same discipline
        # as the host tier (models/preprocessing.py).
        self.batch_transform = batch_transform
        # Per-op terminal narrative (the reference's --trace brackets every
        # loss/gradient/aggregate op with begin/end prints, tools/tf.py:41-58;
        # its graph-level equivalent here is a runtime jax.debug.print after
        # each phase of the step body, value-anchored so the callback sits at
        # the phase boundary in the compiled program).  Debug-cadence only —
        # each device narrates, and the host callback costs real time.
        self.trace_ops = bool(trace_ops)
        # Opt-in per-worker suspicion diagnostics (worker_sq_dist / worker_
        # participation metrics); off by default — the extra O(n·d) pass is
        # a measurable HBM tax at scale.
        self.worker_metrics = bool(worker_metrics)
        # In-step health probe (guardian/probe.py): finite-loss flag, update
        # norm, EMA loss-spike score, per-worker NaN-row flags, nested under
        # metrics["probe"].  On by default — it reuses values the step
        # already computes plus one O(k·d) isfinite pass and an O(n) gather,
        # and adds no dispatches or compiles (tests/test_guardian.py).
        self.health_probe = bool(health_probe)
        # Reputation-gated quarantine: an EMA of a per-step rank signal
        # (1 if the worker's RAW gradient is among the n-f closest to the
        # applied aggregate, else 0); workers whose reputation falls below
        # the threshold have their row masked NaN for that round — the
        # engine treats them exactly like fully-lossy workers, so the rule
        # must absorb NaN rows.  The signal is measured on the raw
        # (pre-quarantine) submissions, so an honest worker whose gradients
        # re-approach the aggregate recovers and is re-admitted.
        self.reputation_decay, self.quarantine_threshold = validate_reputation_args(
            gar, reputation_decay, quarantine_threshold
        )
        # granularity:leaf applies the rule PER PARAMETER LEAF (per-layer
        # selection — the sharded engine's semantics on a plain worker mesh,
        # including n vmapped workers on one chip).  Memory shifts from the
        # dimension-sharded O(d) blocks to one (n, d_leaf) gather at a time,
        # and distance work is replicated per device instead of sharded —
        # the price of letting every layer pick its own honest set.
        if granularity not in ("vector", "leaf"):
            raise UserException("granularity must be vector or leaf (got %r)" % (granularity,))
        self.granularity = granularity
        # Two numerically-equivalent leaf implementations (identical
        # selections and PRNG keys; values agree to float tolerance —
        # vmapped reductions need not lower bit-exactly), dispatched by backend
        # (measured, BENCHMARKS.md row 6b): stacking same-shaped leaves into
        # one vmapped rule call per distinct size is the TPU-shaped program
        # (O(#shapes) collectives/kernels instead of O(#leaves)), but on
        # XLA:CPU the batched sorts/selects lower WORSE than the plain loop
        # (ResNet-50: 157 vs 93 s/step on the 1-core host).  "auto" picks
        # bucketed on TPU, unrolled elsewhere; True/False force it.
        if leaf_bucketing != "auto":
            if not isinstance(leaf_bucketing, bool):
                # 1/0 would pass a tuple-membership check (bool-int equality)
                # yet miss an `is True` dispatch — normalize strictly instead
                raise UserException(
                    "leaf_bucketing must be 'auto' or a bool (got %r)" % (leaf_bucketing,)
                )
        self.leaf_bucketing = leaf_bucketing
        # History-aware robustness (Karimireddy et al. 2021): with
        # worker_momentum = beta in (0, 1), every worker sends its momentum
        # m_i <- beta*m_i + (1-beta)*g_i instead of the raw gradient, so the
        # GAR aggregates slow-moving honest statistics that a fresh-noise
        # Byzantine strategy cannot track.  Carried worker-sharded.
        self.worker_momentum = None if worker_momentum is None else float(worker_momentum)
        if self.worker_momentum is not None and not 0.0 < self.worker_momentum < 1.0:
            raise UserException("worker_momentum must lie in (0, 1), got %r" % worker_momentum)
        # Wire precision: the all_to_all + all_gather carry ~2d floats per
        # device per step (the dominant wire cost, module docstring); bf16
        # halves it.  Gradients are quantized ONCE before the reshard and all
        # GAR math runs in f32 on the upcast values, so every device still
        # sees bit-identical inputs (replicated-update determinism holds).
        # float32 normalizes to None (no quantization path compiled in).
        dt = jnp.dtype(exchange_dtype) if exchange_dtype else None
        self.exchange_dtype = None if dt == jnp.float32 else dt
        self.nb_devices = mesh.shape[worker_axis]
        if self.nb_workers % self.nb_devices != 0:
            raise UserException(
                "nb_workers (%d) must be a multiple of the worker mesh axis (%d)"
                % (self.nb_workers, self.nb_devices)
            )
        self.workers_per_device = self.nb_workers // self.nb_devices
        if self.nb_real_byz > self.nb_workers:
            raise UserException("More real Byzantine workers than workers")
        if attack is not None and self.nb_real_byz == 0:
            raise UserException("An attack needs --nb-real-byz-workers > 0 to have anyone to run it")
        # CLEVER stale infill needs the previously-received gradients carried
        # across steps (mpi_rendezvous_mgr.patch:833-835); stale-mode chaos
        # stragglers reuse the exact same carry (chaos/stragglers.py).
        self.carries_gradients = (lossy_link is not None and lossy_link.clever) or (
            self.chaos is not None and self.chaos.needs_carry
        )
        # Authenticated submission (secure/submit.py): every worker's
        # post-transport row is reduced to a tiny checksum INSIDE the one
        # compiled step (zero added dispatches/recompiles — the compile
        # count is identical with secure on or off, asserted by
        # tests/test_secure.py); rows whose tags cannot verify (chaos
        # forge/tamper) are masked NaN before stacking, and the digests +
        # verdicts ride metrics["secure"] to the host where the real HMAC
        # sign/verify runs one dispatch behind (cli/runner.py).
        self.secure = bool(secure)
        # Flight recorder (obs/flight.py): per-step telemetry lanes written
        # in-scan into a ring carried as a TrainState side buffer, fetched
        # by the host only at summary cadence.  Same compiled program shape
        # discipline as the probe: the ring rides the one executable, so
        # the compile count equals the recorder-off run (tests/
        # test_flight.py asserts).
        self.flight = flight
        if flight is not None:
            flight.validate_for(
                nb_workers=self.nb_workers, probe=self.health_probe,
                worker_metrics=self.worker_metrics,
                chaos=self.chaos is not None, secure=self.secure,
            )
        # jitted slice-concat executables for assemble_batches, per slice count
        self._assemble_cache = {}

    # ------------------------------------------------------------------ #

    def _worker_gradients(self, params, batch_shard, loss_fn):
        """vmap the local k workers' loss/grad; returns ((k,) losses, (k, d) grads, flatmap)."""

        def one(worker_batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, worker_batch)
            return loss, grads

        losses, grads = jax.vmap(one)(batch_shard)
        k = self.workers_per_device
        leaves = jax.tree_util.tree_leaves(grads)
        gvecs = jnp.concatenate([leaf.reshape(k, -1).astype(jnp.float32) for leaf in leaves], axis=1)
        flatmap = FlatMap(jax.tree_util.tree_map(lambda g: g[0], grads))
        return losses, gvecs, flatmap

    def _perturb_local(self, gvecs, key, carry=None, ridx=None):
        """Apply local attack + lossy link + chaos regime + the submission-
        forgery pipeline to each local worker's own slot.

        Returns (perturbed (k, d), new_carry, secure_info) — ``new_carry``
        is the post-transport gradients, i.e. what "the PS received" this
        step: exactly the stale value a lost packet keeps under CLEVER
        infill, and the value a stale-mode straggler keeps re-submitting (a
        worker late k steps in a row re-sends the same gradient k times).
        ``secure_info`` (None unless ``secure``) carries the per-local-
        worker submitted/received digests and the forge/reject verdicts —
        what the host-side authenticator signs and verifies one dispatch
        behind (secure/submit.py).
        """
        from ..secure.submit import FORGE_SCALE, row_digest, tamper_row

        k = self.workers_per_device
        didx = jax.lax.axis_index(worker_axis)
        chaos_forgery = self.chaos is not None and self.chaos.has_forgery
        out = []
        carry_rows = []  # post-transport, PRE-forgery (see carry note below)
        sec = {"digest_sent": [], "digest_recv": [], "forged": [], "rejected": []}
        for j in range(k):
            gidx = didx * k + j
            g = gvecs[j]
            wkey = jax.random.fold_in(key, gidx)
            previous = carry[j] if carry is not None else None
            if self.attack is not None and not self.attack.omniscient:
                forged = self.attack.apply_local(g, jax.random.fold_in(wkey, 1))
                g = jnp.where(gidx < self.nb_real_byz, forged, g)
            if self.chaos is not None and self.chaos.has_local_attacks:
                forged = self.chaos.apply_local_attacks(ridx, g, jax.random.fold_in(wkey, 1))
                g = jnp.where(gidx < self.nb_real_byz, forged, g)
            if self.lossy_link is not None:
                g = self.lossy_link.apply(g, jax.random.fold_in(wkey, 2), gidx, previous=previous)
            if self.chaos is not None:
                if self.chaos.has_drop:
                    # chaos loss storms hit EVERY worker (link sized n); the
                    # rate is the regime's traced scalar — no recompilation
                    g = self.chaos.link.apply(
                        g, jax.random.fold_in(wkey, 2), gidx,
                        drop_rate=self.chaos.drop_rate(ridx),
                    )
                if self.chaos.has_stragglers:
                    late = self.chaos.stragglers.is_late(
                        wkey, gidx, self.chaos.straggler_rate(ridx)
                    )
                    g = self.chaos.stragglers.apply(
                        g, late, self.chaos.straggler_stale(ridx), previous=previous
                    )
            # The carry captures the row HERE — post-transport, PRE-forgery
            # (the sharded engine's convention): a stale straggler re-sends
            # the worker's own last submission, not the impostor's noise or
            # the aggregator's NaN rejection (a rejected step must not leak
            # extra NaN rows into later steps' f accounting).
            carry_rows.append(g)
            # Submission forgery pipeline (docs/security.md).  Order matters:
            # an impersonator REPLACES the submission (and will sign it with
            # a key it does not have), the sender-side digest covers what was
            # submitted, tampering corrupts bits AFTER signing, the receiver
            # digests what arrived — and under ``secure`` a row whose tag
            # cannot verify is rejected to NaN before stacking (absorbed by
            # the GARs within the same f budget as a lossy row).  Fold tags
            # 5/6 keep the forge/tamper streams disjoint from attack (1),
            # lossy (2), augment (3) and sampling (4).
            is_forge = is_tamper = None
            if chaos_forgery:
                fkey = jax.random.fold_in(wkey, 5)
                is_forge = (gidx < self.nb_real_byz) & jax.random.bernoulli(
                    fkey, self.chaos.forge_rate(ridx)
                )
                impostor = jax.random.normal(
                    jax.random.fold_in(fkey, 1), g.shape, g.dtype
                ) * jnp.asarray(FORGE_SCALE, g.dtype)
                g = jnp.where(is_forge, impostor, g)
            sent_digest = None
            if self.secure:
                sent_digest = row_digest(g)
                sec["digest_sent"].append(sent_digest)
            if chaos_forgery:
                tkey = jax.random.fold_in(wkey, 6)
                is_tamper = (gidx < self.nb_real_byz) & jax.random.bernoulli(
                    tkey, self.chaos.tamper_rate(ridx)
                )
                g = jnp.where(is_tamper, tamper_row(g, jax.random.fold_in(tkey, 1)), g)
            if self.secure:
                # without in-transit transforms the received bytes ARE the
                # submitted bytes — reuse the checksum instead of paying a
                # second O(d) pass (half the digest tax of the common case)
                sec["digest_recv"].append(
                    row_digest(g) if chaos_forgery else sent_digest
                )
                forged_flag = is_forge if is_forge is not None else jnp.bool_(False)
                rejected = forged_flag
                if is_tamper is not None:
                    rejected = rejected | is_tamper
                sec["forged"].append(forged_flag)
                sec["rejected"].append(rejected)
                g = jnp.where(rejected, jnp.nan, g)
            out.append(g)
        stacked = jnp.stack(out, axis=0)
        carry = jnp.stack(carry_rows, axis=0) if self.carries_gradients else None
        secure_info = None
        if self.secure:
            secure_info = {
                key_: jnp.stack(values) for key_, values in sec.items()
            }
        return stacked, carry, secure_info

    def _reshard_to_blocks(self, gvecs, d):
        """(k, d) worker-sharded -> (n, d_block) dimension-sharded column block."""
        W, k = self.nb_devices, self.workers_per_device
        if self.exchange_dtype is not None:
            gvecs = gvecs.astype(self.exchange_dtype)
        blk = -(-d // W)
        padded = jnp.pad(gvecs, ((0, 0), (0, W * blk - d)))
        pieces = padded.reshape(k, W, blk).transpose(1, 0, 2)  # (W, k, blk)
        if W == 1:
            gathered = pieces
        else:
            gathered = jax.lax.all_to_all(pieces, worker_axis, split_axis=0, concat_axis=0, tiled=True)
            gathered = gathered.reshape(W, k, blk)
        return gathered.reshape(self.nb_workers, blk)

    def _prepare_rows(self, rows, attack_key, reputation, ridx=None):
        """The ORDER-SENSITIVE shared front of both aggregation paths:
        omniscient attack -> requantize forged rows -> quarantine mask.

        Returns ``(rows, raw_rows)``: what the rule consumes and the
        post-attack PRE-quarantine rows the reputation signal measures.
        The quarantine mask applies AFTER the omniscient attack so the
        reputation signal sees what attackers actually submitted (masking
        earlier would measure the attacker's honest gradient and never
        suspect it); forged rows are squeezed through the exchange dtype
        because they crossed the same wire as honest ones."""
        forged = False
        if self.attack is not None and self.attack.omniscient:
            byz_mask = jnp.arange(self.nb_workers) < self.nb_real_byz
            rows = self.attack.apply_matrix(rows, byz_mask, attack_key)
            forged = True
        if self.chaos is not None and self.chaos.has_omniscient_attacks:
            byz_mask = jnp.arange(self.nb_workers) < self.nb_real_byz
            rows = self.chaos.apply_omniscient_attacks(ridx, rows, byz_mask, attack_key)
            forged = True
        if forged and self.exchange_dtype is not None:
            # forged rows crossed the same quantized wire as honest ones
            rows = rows.astype(self.exchange_dtype).astype(jnp.float32)
        raw_rows = rows
        if self.quarantine_threshold:
            qmask = quarantine_mask(
                reputation, self.quarantine_threshold, self.gar.nb_byz_workers
            )
            rows = jnp.where(qmask[:, None], jnp.nan, rows)
        return rows, raw_rows

    def _aggregate_block(self, block, key, reputation=None, ridx=None):
        """Omniscient attack, quarantine gate, distances (psum), blockwise GAR.

        Returns ``(agg_block, participation, block, raw_block)`` — the (n,)
        worker participation (or None; computed only under
        ``worker_metrics``), the post-quarantine ``block`` the rule actually
        consumed, and the post-attack PRE-quarantine ``raw_block`` the
        reputation signal measures."""
        block, raw_block = self._prepare_rows(block, key, reputation, ridx=ridx)
        dist2 = None
        if self.gar.needs_distances:
            partial = _partial_pairwise_sq_distances(block)
            dist2 = jax.lax.psum(partial, worker_axis) if self.nb_devices > 1 else partial
            dist2 = jnp.maximum(dist2, 0.0)
        axis = worker_axis if self.nb_devices > 1 else None
        # Replicated per-step key for randomized meta-rules (bucketing's
        # permutation); the reserved tag keeps it disjoint from the
        # per-worker attack/lossy streams.
        from ..gars import GAR_KEY_TAG

        gar_key = jax.random.fold_in(key, GAR_KEY_TAG)
        if self.worker_metrics:
            agg, participation = self.gar.aggregate_block_and_participation(
                block, dist2, axis_name=axis, key=gar_key
            )
            return agg, participation, block, raw_block
        agg = self.gar._call_aggregate(block, dist2, axis_name=axis, key=gar_key)
        return agg, None, block, raw_block

    def _aggregate_per_leaf(self, gvecs, flatmap, key, reputation, ridx=None):
        """granularity:leaf dispatch — bucketed on TPU, unrolled elsewhere
        (numerically equivalent; see ``leaf_bucketing`` in __init__)."""
        on_tpu = self.mesh.devices.flat[0].platform == "tpu"  # where THIS mesh runs
        bucketed = (
            self.leaf_bucketing is True
            or (self.leaf_bucketing == "auto" and on_tpu)
        )
        impl = self._aggregate_per_leaf_bucketed if bucketed else self._aggregate_per_leaf_unrolled
        return impl(gvecs, flatmap, key, reputation, ridx=ridx)

    def _aggregate_per_leaf_bucketed(self, gvecs, flatmap, key, reputation, ridx=None):
        """granularity:leaf — gather and reduce each leaf's (n, d_leaf) rows
        independently (per-layer selection), BUCKETED by leaf size.

        Same-sized leaves are stacked into one (L, n, d_leaf) tensor and
        reduced by a single vmapped rule call behind a single all_gather —
        so a ResNet-50 (~160 leaves, ~dozens of distinct shapes) traces
        O(#distinct sizes) collectives and selection graphs instead of
        O(#leaves) (the compile-time/step-latency blowup VERDICT r2 flagged;
        same stacking trick as the sharded engine's layer axis,
        sharded_engine.py).  Per-leaf PRNG keys reproduce the unrolled
        path's exactly (fold_in by ORIGINAL leaf index), so the two paths
        make the same selections and agree with
        ``_aggregate_per_leaf_unrolled`` to float tolerance (vmapped
        reductions are not guaranteed to lower bit-exactly) — asserted by
        tests/test_engine.py.

        Returns ``(agg, participation, wdist, rep_dist)``: the concatenated
        (d,) aggregate (identical on every device), the mean per-leaf
        participation (or None), and the full per-worker squared distances
        to the aggregate over the post-quarantine and raw rows respectively
        (None unless the corresponding feature is on).  No psums needed:
        every device sees complete rows."""
        from ..gars import GAR_KEY_TAG
        from ..gars.common import pairwise_sq_distances

        W = self.nb_devices
        base_key = jax.random.fold_in(key, GAR_KEY_TAG)
        participation_sum = jnp.zeros((self.nb_workers,), jnp.float32)
        participation_count = 0
        wdist = jnp.zeros((self.nb_workers,), jnp.float32) if self.worker_metrics else None
        rep_dist = (
            jnp.zeros((self.nb_workers,), jnp.float32)
            if self.reputation_decay is not None else None
        )

        buckets = {}  # size -> list of (leaf_index, offset), flattening order
        for i, (_, offset, size, _, _) in enumerate(flatmap.slices):
            buckets.setdefault(size, []).append((i, offset))

        concat_parts = []  # per-bucket (L * size,) aggregates
        perm = np.empty((flatmap.size,), np.int32)  # output slot -> concat slot
        pos = 0
        for size, entries in buckets.items():
            idxs = jnp.asarray([i for i, _ in entries], jnp.int32)
            local = jnp.stack(
                [gvecs[:, off:off + size] for _, off in entries], axis=0
            )  # (L, k, size) — static slices, one tensor on the wire
            if self.exchange_dtype is not None:
                local = local.astype(self.exchange_dtype)  # wire precision
            if W > 1:
                gathered = jax.lax.all_gather(local, worker_axis)  # (W, L, k, size)
                rows = gathered.transpose(1, 0, 2, 3).reshape(
                    len(entries), self.nb_workers, size
                )
            else:
                rows = local
            rows = rows.astype(jnp.float32)

            def per_leaf(leaf_rows, leaf_index):
                prep_key = jax.random.fold_in(key, 20_000 + leaf_index)
                leaf_rows, raw_rows = self._prepare_rows(leaf_rows, prep_key, reputation, ridx=ridx)
                dist2 = (
                    jnp.maximum(pairwise_sq_distances(leaf_rows), 0.0)
                    if self.gar.needs_distances else None
                )
                leaf_key = jax.random.fold_in(base_key, leaf_index)
                if self.worker_metrics:
                    agg_leaf, part = self.gar.aggregate_block_and_participation(
                        leaf_rows, dist2, axis_name=None, key=leaf_key
                    )
                else:
                    agg_leaf = self.gar._call_aggregate(
                        leaf_rows, dist2, axis_name=None, key=leaf_key
                    )
                    part = None
                return agg_leaf.astype(jnp.float32), part, leaf_rows, raw_rows

            # (vmapped rule calls: the Pallas auto-tier detects the
            # batching trace centrally and stays on jnp — gars/common.py
            # _is_batched_tracer)
            aggs, parts, prep_rows, raw_rows = jax.vmap(per_leaf)(rows, idxs)
            if parts is not None:
                participation_sum = participation_sum + jnp.sum(parts, axis=0)
                participation_count += len(entries)
            if wdist is not None:
                diff = prep_rows - aggs[:, None, :]
                wdist = wdist + jnp.sum(diff * diff, axis=(0, 2))
            if rep_dist is not None:
                rdiff = raw_rows - aggs[:, None, :]
                rep_dist = rep_dist + jnp.sum(rdiff * rdiff, axis=(0, 2))
            concat_parts.append(aggs.reshape(-1))
            for j, (_, off) in enumerate(entries):
                perm[off:off + size] = np.arange(
                    pos + j * size, pos + (j + 1) * size, dtype=np.int32
                )
            pos += len(entries) * size

        if not concat_parts:
            return jnp.zeros((0,), jnp.float32), None, wdist, rep_dist
        agg = jnp.concatenate(concat_parts)[perm]  # back to flattening order
        participation = (
            participation_sum / participation_count if participation_count else None
        )
        return agg, participation, wdist, rep_dist

    def _aggregate_per_leaf_unrolled(self, gvecs, flatmap, key, reputation, ridx=None):
        """The plain per-leaf loop (one all_gather + one rule call per
        leaf).  Semantically the definition of granularity:leaf — and the
        DEFAULT path off-TPU (``leaf_bucketing="auto"``; measured faster
        than the batched form on XLA:CPU, BENCHMARKS.md row 6b), CLI-
        reachable via ``--leaf-bucketing off`` anywhere."""
        from ..gars import GAR_KEY_TAG
        from ..gars.common import pairwise_sq_distances

        W = self.nb_devices
        base_key = jax.random.fold_in(key, GAR_KEY_TAG)
        agg_parts = []
        participation_sum = jnp.zeros((self.nb_workers,), jnp.float32)
        participation_count = 0
        wdist = jnp.zeros((self.nb_workers,), jnp.float32) if self.worker_metrics else None
        rep_dist = (
            jnp.zeros((self.nb_workers,), jnp.float32)
            if self.reputation_decay is not None else None
        )
        for i, (_, offset, size, _, _) in enumerate(flatmap.slices):
            local = gvecs[:, offset:offset + size]  # static slice
            if self.exchange_dtype is not None:
                local = local.astype(self.exchange_dtype)  # wire precision
            if W > 1:
                rows = jax.lax.all_gather(local, worker_axis).reshape(self.nb_workers, size)
            else:
                rows = local
            rows = rows.astype(jnp.float32)
            rows, raw_rows = self._prepare_rows(
                rows, jax.random.fold_in(key, 20_000 + i), reputation, ridx=ridx
            )
            dist2 = (
                jnp.maximum(pairwise_sq_distances(rows), 0.0)
                if self.gar.needs_distances else None
            )
            leaf_key = jax.random.fold_in(base_key, i)
            if self.worker_metrics:
                agg_leaf, part = self.gar.aggregate_block_and_participation(
                    rows, dist2, axis_name=None, key=leaf_key
                )
                if part is not None:
                    participation_sum = participation_sum + part
                    participation_count += 1
            else:
                agg_leaf = self.gar._call_aggregate(rows, dist2, axis_name=None, key=leaf_key)
            if wdist is not None:
                diff = rows - agg_leaf[None, :]
                wdist = wdist + jnp.sum(diff * diff, axis=1)
            if rep_dist is not None:
                rdiff = raw_rows - agg_leaf.astype(jnp.float32)[None, :]
                rep_dist = rep_dist + jnp.sum(rdiff * rdiff, axis=1)
            agg_parts.append(agg_leaf.astype(jnp.float32))
        agg = jnp.concatenate(agg_parts) if agg_parts else jnp.zeros((0,), jnp.float32)
        participation = (
            participation_sum / participation_count if participation_count else None
        )
        return agg, participation, wdist, rep_dist

    # ------------------------------------------------------------------ #

    def _state_spec(self):
        """PartitionSpec prefix tree for TrainState: everything replicated
        except the worker-sharded side buffers (CLEVER carry, momentum)."""
        return TrainState(
            step=P(),
            params=P(),
            opt_state=P(),
            rng=P(),
            carry=P(worker_axis) if self.carries_gradients else None,
            momentum=P(worker_axis) if self.worker_momentum is not None else None,
            momentum_steps=P() if self.worker_momentum is not None else None,
            reputation=P() if self.reputation_decay is not None else None,
            loss_ema=P() if self.health_probe else None,
            flight=P() if self.flight is not None else None,
        )

    def _make_body(self, loss_fn, tx):
        """The per-step SPMD body shared by build_step and build_multi_step."""
        W = self.nb_devices

        def body(state, batch):
            def mark(fmt, **kw):
                # Anchored on the values it prints, so the callback cannot be
                # hoisted across the phase it brackets (XLA preserves the
                # data dependency; pure prints could reorder freely).
                if self.trace_ops:
                    jax.debug.print(
                        "TRACE step {step} dev {dev} " + fmt,
                        step=state.step, dev=jax.lax.axis_index(worker_axis), **kw)

            key = jax.random.fold_in(state.rng, state.step)
            # Active chaos regime for THIS step: a traced array index into
            # the schedule's compiled knob vectors, so regime switches land
            # at exactly their scheduled step with zero recompilation.
            ridx = self.chaos.regime_index(state.step) if self.chaos is not None else None
            if self.batch_transform is not None:
                k = self.workers_per_device
                didx = jax.lax.axis_index(worker_axis)

                def aug_one(worker_batch, j):
                    # fold tag 3: disjoint from the attack (1) / lossy (2)
                    # streams derived from the same (key, global worker) pair
                    wkey = jax.random.fold_in(jax.random.fold_in(key, didx * k + j), 3)
                    return self.batch_transform(worker_batch, wkey)

                batch = jax.vmap(aug_one)(batch, jnp.arange(k))
            losses, gvecs, flatmap = self._worker_gradients(state.params, batch, loss_fn)
            mark("losses+gradients done: local loss sum {l}", l=jnp.sum(losses))
            new_momentum, new_momentum_steps = None, None
            if self.worker_momentum is not None:
                # Honest workers send momenta (computed BEFORE the attack:
                # attackers forge what they transmit, not what honest peers
                # remember).  Bias-corrected like Adam so early steps are not
                # (1-beta)-scaled relative to plain gradients; the correction
                # counts momentum updates, NOT the global step — the buffer
                # re-zeroes on restore and its warmup must restart with it.
                beta = self.worker_momentum
                new_momentum = beta * state.momentum + (1.0 - beta) * gvecs
                new_momentum_steps = state.momentum_steps + 1
                gvecs = new_momentum / (1.0 - beta ** new_momentum_steps.astype(jnp.float32))
            gvecs, new_carry, secure_info = self._perturb_local(
                gvecs, key, carry=state.carry, ridx=ridx
            )
            d = gvecs.shape[-1]
            if self.granularity == "leaf":
                agg, participation, wdist, rep_dist = self._aggregate_per_leaf(
                    gvecs, flatmap, key, state.reputation, ridx=ridx
                )
            else:
                block = self._reshard_to_blocks(gvecs, d)
                if self.exchange_dtype is not None:
                    block = block.astype(jnp.float32)  # GAR math always in f32
                agg_block, participation, seen_block, raw_block = self._aggregate_block(
                    block, key, reputation=state.reputation, ridx=ridx
                )
                if self.exchange_dtype is not None:
                    agg_block = agg_block.astype(self.exchange_dtype)  # wire, leg 2
                if W > 1:
                    agg = jax.lax.all_gather(agg_block, worker_axis, axis=0).reshape(-1)[:d]
                else:
                    agg = agg_block[:d]
                agg = agg.astype(jnp.float32)
                wdist = rep_dist = None
                if self.worker_metrics:
                    # distances over what the aggregator actually saw
                    # (post-attack, post-lossy, post-quarantine)
                    diff = seen_block - agg_block[None, :]
                    wdist = jnp.sum(diff * diff, axis=1)
                    if W > 1:
                        wdist = jax.lax.psum(wdist, worker_axis)
                if self.reputation_decay is not None:
                    rdiff = raw_block - agg_block.astype(jnp.float32)[None, :]
                    rep_dist = jnp.sum(rdiff * rdiff, axis=1)
                    if W > 1:
                        rep_dist = jax.lax.psum(rep_dist, worker_axis)
            new_reputation = state.reputation
            if self.reputation_decay is not None:
                # Rank signal on the RAW submissions (post-ALL-attacks,
                # pre-quarantine): 1 if among the n-f closest to the applied
                # aggregate AND finite — NaN-infilled lossy rows read +inf
                # -> signal 0 (the finiteness gate stops +inf index-ties
                # from boosting low-index dead workers).
                from ..gars.common import nonfinite_to_inf, smallest_k_mask

                signal = smallest_k_mask(
                    nonfinite_to_inf(rep_dist),
                    self.nb_workers - self.gar.nb_byz_workers,
                ).astype(jnp.float32) * jnp.isfinite(rep_dist).astype(jnp.float32)
                beta = self.reputation_decay
                new_reputation = beta * state.reputation + (1.0 - beta) * signal
            mark("aggregate done: |agg| {g}", g=jnp.linalg.norm(agg))
            agg_tree = flatmap.inflate(agg)
            updates, opt_state = tx.update(agg_tree, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            mark("apply done: |p0| {p}",
                 p=jnp.linalg.norm(jax.tree_util.tree_leaves(params)[0]))
            total_loss = jax.lax.psum(jnp.sum(losses), worker_axis) if W > 1 else jnp.sum(losses)
            update_norm = jnp.linalg.norm(agg)
            new_loss_ema = state.loss_ema
            probe_fields = None
            if self.health_probe:
                from ..guardian import probe as health

                # Per-worker NaN-row flags measure the POST-TRANSPORT
                # submissions (what the aggregation actually received:
                # lossy NaN infill, dropped stragglers, inf attacks) —
                # distinct from loss_finite, which measures model health.
                local_bad = jnp.any(~jnp.isfinite(gvecs), axis=1)  # (k,)
                if W > 1:
                    worker_nan = jax.lax.all_gather(local_bad, worker_axis).reshape(
                        self.nb_workers
                    )
                else:
                    worker_nan = local_bad
                probe_fields = health.probe_metrics(
                    total_loss, update_norm,
                    health.spike_score(total_loss, state.loss_ema), worker_nan,
                )
                new_loss_ema = health.update_loss_ema(state.loss_ema, total_loss)
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state,
                carry=new_carry, momentum=new_momentum, momentum_steps=new_momentum_steps,
                reputation=new_reputation, loss_ema=new_loss_ema,
            )
            metrics = {
                "total_loss": total_loss,
                "grad_norm": update_norm,
            }
            if probe_fields is not None:
                metrics[health.PROBE_KEY] = probe_fields
            if secure_info is not None:
                # Submission authentication material for the host-side
                # sign/verify (secure/submit.py): per-worker digests of what
                # was submitted vs received, plus the forge/reject verdicts.
                # Gathered worker-major like the probe's NaN flags.
                def gather_workers(local):
                    if W > 1:
                        gathered = jax.lax.all_gather(local, worker_axis)
                        return gathered.reshape((self.nb_workers,) + local.shape[1:])
                    return local

                metrics["secure"] = {
                    name: gather_workers(value)
                    for name, value in secure_info.items()
                }
            if ridx is not None:
                # replicated scalar (a pure function of the replicated step)
                # — the observability layer's regime column
                metrics["chaos_regime"] = ridx
            if self.worker_metrics:
                # Suspicion diagnostics: squared distance of each worker's
                # gradient to the aggregate (universal), plus the rule's own
                # per-worker participation weight when it selects by worker.
                metrics["worker_sq_dist"] = wdist
                if participation is not None:
                    metrics["worker_participation"] = participation
                if self.reputation_decay is not None:
                    metrics["worker_reputation"] = new_reputation
                    if self.quarantine_threshold:
                        metrics["nb_quarantined"] = jnp.sum(
                            quarantine_mask(
                                state.reputation, self.quarantine_threshold,
                                self.gar.nb_byz_workers,
                            ).astype(jnp.int32)
                        )
            if self.flight is not None:
                # In-scan flight-recorder write (obs/flight.py): each lane
                # stores the exact traced value the metrics dict carries,
                # so ring rows are bit-identical to per-step metrics by
                # construction.
                new_state = new_state.replace(
                    flight=self.flight.record(state.flight, state.step, metrics)
                )
            return new_state, metrics

        return body

    def build_step(self, loss_fn, tx):
        """Build the jitted robust training step.

        Args:
          loss_fn: (params, worker_batch) -> scalar loss.
          tx: optax GradientTransformation.
        Returns:
          step(state, batch) -> (state, metrics) with ``batch`` pytrees of
          leading dimension nb_workers (worker-major), sharded over the mesh.
        """
        body = self._make_body(loss_fn, tx)
        sharded = compat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(self._state_spec(), P(worker_axis)),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        # The span wrapper is HOST-side only (obs/trace.py): it never touches
        # the jitted callable, so the compile count is identical with tracing
        # on or off (tests/test_obs.py asserts), and attribute access
        # (``_cache_size``) falls through to the jit.
        return trace.traced(
            "train_step.dispatch", jax.jit(sharded, donate_argnums=(0,)), cat="train"
        )

    def build_multi_step(self, loss_fn, tx, repeat_steps=None):
        """Build a jitted K-step trainer: one dispatch runs a whole scan.

        Per-step host dispatch dominates wall time for small models (the
        reference pays this as a full PS round-trip per `sess.run`,
        runner.py:562-576); scanning K steps inside one executable removes
        it. Metrics come back per step (leading K).

        Two forms:
        - ``repeat_steps=None``: ``multi(state, batches)`` with every batch
          leaf leading (K, nb_workers, ...) — K distinct batches.
        - ``repeat_steps=K``: ``multi(state, batch)`` reuses one
          device-resident worker-major batch for K steps (no K-fold host
          transfer; what the throughput bench uses).
        """
        step_body = self._make_body(loss_fn, tx)

        if repeat_steps is None:

            def many(state, batches):
                return jax.lax.scan(step_body, state, batches)

            batch_spec = P(None, worker_axis)
        else:

            def many(state, batch):
                return jax.lax.scan(
                    lambda s, _: step_body(s, batch), state, None, length=int(repeat_steps)
                )

            batch_spec = P(worker_axis)

        sharded = compat.shard_map(
            many,
            mesh=self.mesh,
            in_specs=(self._state_spec(), batch_spec),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        return trace.traced(
            "train_multi_step.dispatch", jax.jit(sharded, donate_argnums=(0,)),
            cat="train",
        )

    def build_sampled_multi_step(self, loss_fn, tx, repeat_steps, batch_size):
        """K-step trainer drawing FRESH per-worker batches ON DEVICE each
        step from a device-resident dataset.

        Rationale: on a tunneled TPU the host->device input path is the
        measured bound — config 2 streams at ~2.0 steps/s while the same
        program with the batch already resident runs at ~26 steps/s
        (bench_mini, round 4).  The reference streams each worker's batches
        through a local queue-runner pipeline every step (graph.py:251-254
        places each worker's input ops on that task's CPU; the pipeline
        itself is the experiment's DatasetDataProvider + tf.train.batch +
        prefetch_queue stack, experiments/cnnet.py:127-141); the
        TPU-native equivalent is to transfer the dataset ONCE (CIFAR-10
        train is ~0.6 GB in f32 — a few percent of HBM) and gather each
        worker's sampled rows in-graph, so every step still trains on a
        fresh i.i.d.-with-replacement draw (the same stream semantics as
        ``WorkerBatchIterator``, datasets.py:318-325) but no step pays the
        tunnel.

        Returns ``multi(state, data) -> (state, metrics)`` where ``data`` is
        the dataset pytree (e.g. ``{"image": x_train, "label": y_train}``),
        placed replicated via :meth:`replicate`.  Worker w's step-s draw is
        a pure function of ``(state.rng, s, w)`` — independent of the mesh
        layout, reproducible across restores, and disjoint (fold tag 4) from
        the attack (1) / lossy (2) / augment (3) streams derived from the
        same key.  Device-side augmentation (``batch_transform``) composes
        unchanged: it runs inside the step body on the sampled batch.
        """
        step_body = self._make_body(loss_fn, tx)
        k = self.workers_per_device
        nb_steps = int(repeat_steps)
        batch_size = int(batch_size)

        def many(state, data):
            nb_examples = jax.tree_util.tree_leaves(data)[0].shape[0]

            def sampled_body(s, _):
                key = jax.random.fold_in(s.rng, s.step)
                didx = jax.lax.axis_index(worker_axis)

                def draw(j):
                    # fold tag 4: the data-sampling stream, disjoint from
                    # attack (1) / lossy (2) / augment (3)
                    wkey = jax.random.fold_in(
                        jax.random.fold_in(key, didx * k + j), 4
                    )
                    idx = jax.random.randint(wkey, (batch_size,), 0, nb_examples)
                    return jax.tree_util.tree_map(lambda a: a[idx], data)

                batch = jax.vmap(draw)(jnp.arange(k))
                return step_body(s, batch)

            return jax.lax.scan(sampled_body, state, None, length=nb_steps)

        sharded = compat.shard_map(
            many,
            mesh=self.mesh,
            in_specs=(self._state_spec(), P()),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        return trace.traced(
            "train_sampled_multi_step.dispatch",
            jax.jit(sharded, donate_argnums=(0,)), cat="train",
        )

    def build_gar_probe(self, d, seed=0):
        """Jitted GAR-only executable at the engine's exact (n, d) and
        sharding — the measurement instrument behind the runner's
        ``gar_seconds_total`` / ``gar.aggregate`` telemetry.

        Returns ``probe(step)``: one full aggregation (psum-completed
        distances + the rule's blockwise reduction — the same path the
        compiled train step runs in phase 5/6 of the module docstring) over
        a persistent synthetic device-resident row matrix.  Attacks, lossy
        links and quarantine are deliberately excluded: the probe times the
        RULE at the run's real (n, d), not the adversity simulation.  The
        caller times ``jax.block_until_ready(probe(step))``; ``step`` folds
        into the rule key so randomized meta-rules (bucketing/hier) redraw
        like they do in training."""
        from ..gars import GAR_KEY_TAG

        W = self.nb_devices
        blk = -(-int(d) // W)
        # Generate the synthetic rows ON DEVICE under jit with an explicit
        # output sharding: GSPMD shards the generation itself, so the host
        # never materializes the (n, d) matrix (n x the model footprint at
        # the large n the probe exists to measure).
        make_rows = jax.jit(
            lambda k: jax.random.normal(k, (self.nb_workers, W * blk), jnp.float32),
            out_shardings=jax.sharding.NamedSharding(self.mesh, P(None, worker_axis)),
        )
        rows = make_rows(jax.random.PRNGKey(seed))

        def body(block, key):
            dist2 = None
            if self.gar.needs_distances:
                partial = _partial_pairwise_sq_distances(block)
                dist2 = jax.lax.psum(partial, worker_axis) if W > 1 else partial
                dist2 = jnp.maximum(dist2, 0.0)
            axis = worker_axis if W > 1 else None
            gar_key = jax.random.fold_in(key, GAR_KEY_TAG)
            return self.gar._call_aggregate(block, dist2, axis_name=axis, key=gar_key)

        sharded = compat.shard_map(
            body, mesh=self.mesh,
            in_specs=(P(None, worker_axis), P()),
            out_specs=P(worker_axis),
            check_vma=False,
        )
        fn = jax.jit(sharded)
        base = jax.random.PRNGKey(seed)

        def probe(step=0):
            return fn(rows, jax.random.fold_in(base, step))

        return probe

    def build_eval_sums(self, metric_fn):
        """Build the jitted evaluation step returning (sum, count) accumulators.

        Exact full-split metrics need sums accumulated across *all* eval
        batches before dividing (the reference evaluates the whole test set in
        one graph pass, experiments/mnist.py:136-148; here the host loop
        accumulates per-batch device sums instead).

        Args:
          metric_fn: (params, worker_batch) -> dict name -> (sum, count).
        Returns:
          eval_step(state, batch) -> dict name -> (sum, count) over the batch.
        """
        W = self.nb_devices

        def body(state, batch):
            sums = jax.vmap(lambda b: metric_fn(state.params, b))(batch)
            folded = jax.tree_util.tree_map(lambda x: jnp.sum(x, axis=0), sums)
            if W > 1:
                folded = jax.lax.psum(folded, worker_axis)
            return folded

        sharded = compat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(self._state_spec(), P(worker_axis)),
            out_specs=P(),
            check_vma=False,
        )
        return trace.traced("eval_step.dispatch", jax.jit(sharded), cat="eval")

    def build_eval(self, metric_fn):
        """Like ``build_eval_sums`` but divides, returning per-batch means."""
        eval_sums = self.build_eval_sums(metric_fn)

        def means(state, batch):
            folded = eval_sums(state, batch)
            return {name: total / jnp.maximum(count, 1) for name, (total, count) in folded.items()}

        return means

    # ------------------------------------------------------------------ #

    def shard_batch(self, batch):
        """Device_put a worker-major batch pytree with the worker sharding."""
        spec = jax.sharding.NamedSharding(self.mesh, P(worker_axis))
        return jax.device_put(batch, spec)

    def shard_batches(self, batches):
        """Device_put a (K, nb_workers, ...) batch stack for build_multi_step.

        The step axis is unsharded, so this also places a chunk SLICE
        ((k_i, nb_workers, ...) for any k_i) — the input pipeline
        (models/datasets.py ChunkPipeline) issues one such transfer per
        slice and re-joins them with :meth:`assemble_batches`."""
        spec = jax.sharding.NamedSharding(self.mesh, P(None, worker_axis))
        return jax.device_put(batches, spec)

    def assemble_batches(self, parts):
        """Concatenate step-axis chunk slices (each ``shard_batches``-placed)
        into the one (K, nb_workers, ...) device chunk ``build_multi_step``
        consumes.  Jitted (cached per slice count), so after the first chunk
        this is a single device-side executable whose output is a FRESH
        buffer — the input pipeline's host ping-pong buffers are safe to
        reuse once it has run, even if a backend aliased a ``device_put``."""
        fn = self._assemble_cache.get(len(parts))
        if fn is None:
            fn = jax.jit(lambda *xs: jax.tree_util.tree_map(
                lambda *leaves: jnp.concatenate(leaves, axis=0), *xs))
            self._assemble_cache[len(parts)] = fn
        return fn(*parts)

    def replicate(self, tree):
        """Device_put a pytree fully replicated over the mesh."""
        spec = jax.sharding.NamedSharding(self.mesh, P())
        return jax.device_put(tree, spec)

    def _worker_sharded(self, array_or_none, d=None):
        """Device_put (or create zeroed) a (nb_workers, d) worker-sharded buffer."""
        spec = jax.sharding.NamedSharding(self.mesh, P(worker_axis))
        if array_or_none is not None:
            return jax.device_put(array_or_none, spec)
        return jax.jit(lambda: jnp.zeros((self.nb_workers, d), jnp.float32), out_shardings=spec)()

    def put_state(self, state):
        """Device_put a TrainState with the engine's state sharding — fully
        replicated except the worker-sharded side buffers (restore path)."""
        carry, momentum = state.carry, state.momentum
        placed = self.replicate(state.replace(carry=None, momentum=None))
        if carry is not None:
            carry = self._worker_sharded(carry)
        if momentum is not None:
            momentum = self._worker_sharded(momentum)
        return placed.replace(carry=carry, momentum=momentum)

    def init_state(self, params, tx, seed=0):
        """Create a replicated TrainState, plus zeroed worker-sharded side
        buffers when enabled: the CLEVER carry (packets lost before any
        gradient was received read as zero contributions, like the
        reference's freshly-allocated reassembly buffer) and the per-worker
        momentum."""
        state = self.replicate(TrainState.create(params, tx, rng=jax.random.PRNGKey(seed)))
        d = sum(leaf.size for leaf in jax.tree_util.tree_leaves(params))
        if self.carries_gradients:
            state = state.replace(carry=self._worker_sharded(None, d))
        if self.worker_momentum is not None:
            state = state.replace(
                momentum=self._worker_sharded(None, d),
                momentum_steps=self.replicate(jnp.zeros((), jnp.int32)),
            )
        if self.reputation_decay is not None:
            # everyone starts trusted; quarantine only after evidence accrues
            state = state.replace(
                reputation=self.replicate(jnp.ones((self.nb_workers,), jnp.float32))
            )
        if self.health_probe:
            from ..guardian.probe import EMA_UNSET

            state = state.replace(
                loss_ema=self.replicate(jnp.float32(EMA_UNSET))
            )
        if self.flight is not None:
            # empty ring, every slot tagged invalid (step -1)
            state = state.replace(
                flight=self.replicate(self.flight.init_buffers())
            )
        return state
